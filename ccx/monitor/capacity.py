"""Broker capacity resolution — the ``BrokerCapacityConfigResolver`` SPI.

Parity: ``config/BrokerCapacityConfigResolver`` + the file-based default
``BrokerCapacityConfigFileResolver`` with its three formats
``capacity.json`` / ``capacityJBOD.json`` / ``capacityCores.json``
(SURVEY.md C5, M6): a JSON list of per-broker entries, broker id ``-1`` as
the default row, DISK either a single number or a {logdir: capacity} map
(JBOD), CPU either a percentage or ``num.cores``. Units follow the
reference: DISK in MB, NW in KB/s, CPU in percent (100 = one core fully
used unless cores-mode normalizes).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from ccx.common.resources import NUM_RESOURCES, Resource


@dataclasses.dataclass(frozen=True)
class BrokerCapacityInfo:
    """Per-broker capacities (+ per-disk breakdown for JBOD)."""

    capacity: tuple[float, ...]            # indexed by Resource
    disk_capacities: tuple[float, ...] = ()  # per logdir, sums to capacity[DISK]
    estimated: bool = False                # True when the default row was used
    num_cores: int = 1

    def resource(self, r: Resource) -> float:
        return self.capacity[r]


class BrokerCapacityResolver:
    """SPI: resolve capacity for a broker at model-build time (ref C5)."""

    def capacity_for(self, broker_id: int) -> BrokerCapacityInfo:
        raise NotImplementedError


DEFAULT_BROKER_ID = -1


class FileCapacityResolver(BrokerCapacityResolver):
    """Reads the reference's capacity JSON formats.

    ``{"brokerCapacities": [{"brokerId": "-1", "capacity": {"DISK": "100000",
    "CPU": "100", "NW_IN": "10000", "NW_OUT": "10000"}}, ...]}``; JBOD DISK =
    ``{"/logdir1": "50000", ...}``; cores-mode CPU = ``{"num.cores": "8"}``.
    """

    def __init__(self, path: str | None = None, config=None) -> None:
        if path is None and config is not None:
            path = config["capacity.config.file"]
        self._by_broker: dict[int, BrokerCapacityInfo] = {}
        if path:
            self._load(path)

    def configure(self, config) -> None:
        if not self._by_broker:
            self._load(config["capacity.config.file"])

    def _load(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        for entry in doc.get("brokerCapacities", []):
            broker_id = int(entry["brokerId"])
            cap = entry["capacity"]
            disk = cap.get("DISK", 0)
            if isinstance(disk, dict):  # JBOD: logdir -> capacity
                disks = tuple(float(v) for v in disk.values())
                disk_total = float(sum(disks))
            else:
                disks = (float(disk),)
                disk_total = float(disk)
            cpu = cap.get("CPU", 100.0)
            num_cores = 1
            if isinstance(cpu, dict):  # capacityCores.json mode
                num_cores = int(cpu["num.cores"])
                cpu_total = 100.0 * num_cores
            else:
                cpu_total = float(cpu)
            vec = [0.0] * NUM_RESOURCES
            vec[Resource.CPU] = cpu_total
            vec[Resource.NW_IN] = float(cap.get("NW_IN", 0))
            vec[Resource.NW_OUT] = float(cap.get("NW_OUT", 0))
            vec[Resource.DISK] = disk_total
            self._by_broker[broker_id] = BrokerCapacityInfo(
                tuple(vec), disks, estimated=False, num_cores=num_cores
            )
        if DEFAULT_BROKER_ID not in self._by_broker:
            raise ValueError(
                f"capacity file {path} has no default entry (brokerId -1)"
            )

    def capacity_for(self, broker_id: int) -> BrokerCapacityInfo:
        info = self._by_broker.get(broker_id)
        if info is not None:
            return info
        d = self._by_broker[DEFAULT_BROKER_ID]
        return dataclasses.replace(d, estimated=True)


class StaticCapacityResolver(BrokerCapacityResolver):
    """Uniform capacities for tests/simulation."""

    def __init__(self, capacity: dict[Resource, float] | None = None,
                 num_disks: int = 1, config=None) -> None:
        cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
               Resource.DISK: 1e6}
        cap.update(capacity or {})
        vec = tuple(cap[Resource(i)] for i in range(NUM_RESOURCES))
        per_disk = cap[Resource.DISK] / num_disks
        self._info = BrokerCapacityInfo(vec, tuple([per_disk] * num_disks))

    def capacity_for(self, broker_id: int) -> BrokerCapacityInfo:
        return self._info


def capacity_matrix(resolver: BrokerCapacityResolver,
                    broker_ids: list[int]) -> np.ndarray:
    """float64[RES, B] capacity tensor column for build_model."""
    out = np.zeros((NUM_RESOURCES, len(broker_ids)))
    for i, b in enumerate(broker_ids):
        out[:, i] = resolver.capacity_for(b).capacity
    return out


def disk_capacity_matrix(resolver: BrokerCapacityResolver,
                         broker_ids: list[int]) -> np.ndarray:
    """float64[B, D_max] per-disk capacities, zero-padded."""
    infos = [resolver.capacity_for(b) for b in broker_ids]
    d_max = max((len(i.disk_capacities) for i in infos), default=1) or 1
    out = np.zeros((len(broker_ids), d_max))
    for i, info in enumerate(infos):
        disks = info.disk_capacities or (info.capacity[Resource.DISK],)
        out[i, : len(disks)] = disks
    return out
