"""Virtual host-mesh setup — ONE implementation of the XLA_FLAGS dance.

Forcing the CPU platform with N virtual XLA devices (so the full
pjit/shard_map path runs without TPU hardware) used to be copy-pasted in
three places (tests/conftest.py, tools/probe_sharded.py,
__graft_entry__.py) and was about to grow a fourth (bench.py --scaling).
Every copy had the same two subtleties, now encoded once:

* the environment preloads jax via sitecustomize (axon TPU platform), so
  ``JAX_PLATFORMS`` alone is too late — ``jax.config`` must be updated
  too, which works because backend *initialization* is lazy;
* ``XLA_FLAGS`` is read once at backend init: the flag must be appended
  before any jax array/device call, and never twice (a duplicated
  ``--xla_force_host_platform_device_count`` makes XLA error out).

Call :func:`force_host_devices` before the first backend use; it is
best-effort and silently keeps a pre-existing device-count flag. Callers
that depend on the count (``bench.py --scaling``) use
:func:`ensure_host_devices`, which additionally initializes the backend
and raises when it came up with fewer devices than asked.
"""

from __future__ import annotations

import os

FLAG = "xla_force_host_platform_device_count"


def force_host_devices(n: int = 8) -> None:
    """Force the CPU platform with ``n`` virtual devices (idempotent).

    Must run before the first jax backend initialization — import order
    does not matter (jax may already be imported), backend-touching calls
    do.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if FLAG not in flags:
        os.environ["XLA_FLAGS"] = (flags + f" --{FLAG}={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_host_devices(n: int = 8) -> int:
    """``force_host_devices`` + verify: returns the actual device count,
    raising when the backend came up with fewer devices than asked (the
    flag arrived after backend init)."""
    force_host_devices(n)
    import jax

    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"requested {n} virtual host devices but the backend "
            f"initialized with {have} — force_host_devices() must run "
            "before any jax backend use"
        )
    return have
