"""Windowed SLO engine — the accounting half of the closed control loop.

The reference system is a *continuous self-managing service*: an anomaly
detector watches the cluster and fires self-healing verbs. Rounds 5-18
put the raw signals on the wire (chunk-heartbeat energy, warm-pressure
bands, goal/fleet/devmem gauges); this module turns them into
*objectives* an operator can page on and a soak rung can gate on:

- **warm_served** — fraction of serving windows answered by the warm
  incremental path AND verified (the product's headline promise: drift
  served at steady-state latency, not the cold wall);
- **latency** — fraction of windows whose end-to-end wall landed inside
  the per-window latency budget;
- **violation_free** — fraction of windows with no classified anomaly
  signal (goal violations, dead brokers, devmem pressure) — the
  goal-violation *dwell* objective: how much of the timeline the fleet
  spent in violation.

Each objective is tracked per cluster as TWO sliding windows (short /
long, in serving-window counts) and reported as *burn rates*: the
fraction of the error budget consumed per window interval,
``burn = error_rate / (1 - target)`` — burn 1.0 exactly spends the
budget, >1 is on course to violate the SLO, the classic multi-window
alert pairs the fast window (page) with the slow one (ticket).

The engine also owns the *healing episode* ledger: one episode per
(cluster, violation family) from the first violating signal through the
detector's verb to the first verified-clean window, measuring
time-to-detect and time-to-heal. ``ccx.detector.stream`` drives it from
the live signal stream; ``bench.py --soak`` gates on its numbers;
``tools/bench_ledger.py`` trends them.

Like ``ccx.common.convergence``, this module is deliberately
stdlib-only — no jax, no numpy — so the ledger and the tools import it
instantly without dragging the device stack in.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools

#: objective name -> the window-level predicate it counts (documentation;
#: the engine consumes pre-computed booleans)
OBJECTIVES = ("warm_served", "latency", "violation_free")


def percentile(values, q: float):
    """Nearest-rank percentile of an iterable (None when empty) — the
    same convention the bench rungs use for their p99 walls."""
    vals = sorted(values)
    if not vals:
        return None
    i = min(int(round(q * (len(vals) - 1))), len(vals) - 1)
    return vals[i]


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """Per-cluster objective targets + window geometry (config keys
    ``observability.slo.*``; see ``observability_config_def``)."""

    #: span of one accounting window in (simulated or wall) seconds —
    #: the soak bench advances a simulated clock by this much per tick
    window_s: float = 10.0
    #: short (paging) window, in serving-window counts
    short_windows: int = 12
    #: long (ticket) window, in serving-window counts
    long_windows: int = 60
    #: warm-served fraction target (error budget = 1 - target)
    warm_target: float = 0.95
    #: per-window end-to-end latency budget (seconds); windows at or
    #: under budget count as good
    latency_budget_s: float = 5.0
    #: latency-SLO target fraction
    latency_target: float = 0.99
    #: violation-free (goal-violation dwell) target fraction
    dwell_target: float = 0.95

    @classmethod
    def from_config(cls, config) -> "SloObjectives":
        """Build from the ``observability.slo.*`` keys of a
        CruiseControlConfig (missing keys fall back to the dataclass
        defaults, so plain dicts work in tests)."""
        def g(key, default):
            try:
                return config[key]
            except Exception:  # noqa: BLE001 — absent key -> default
                return default
        return cls(
            window_s=float(g("observability.slo.window.seconds", 10.0)),
            short_windows=int(g("observability.slo.short.windows", 12)),
            long_windows=int(g("observability.slo.long.windows", 60)),
            warm_target=float(g("observability.slo.warm.target", 0.95)),
            latency_budget_s=float(
                g("observability.slo.latency.budget.seconds", 5.0)
            ),
            latency_target=float(
                g("observability.slo.latency.target", 0.99)
            ),
            dwell_target=float(g("observability.slo.dwell.target", 0.95)),
        )

    def target(self, objective: str) -> float:
        return {
            "warm_served": self.warm_target,
            "latency": self.latency_target,
            "violation_free": self.dwell_target,
        }[objective]


class _BoolWindow:
    """A sliding window of good/bad window outcomes."""

    __slots__ = ("_dq",)

    def __init__(self, maxlen: int) -> None:
        self._dq: collections.deque = collections.deque(maxlen=max(maxlen, 1))

    def add(self, ok: bool) -> None:
        self._dq.append(bool(ok))

    @property
    def seen(self) -> int:
        return len(self._dq)

    def error_rate(self) -> float | None:
        if not self._dq:
            return None
        return 1.0 - (sum(self._dq) / len(self._dq))


@dataclasses.dataclass
class HealingEpisode:
    """One detected -> verb fired -> recovered arc, with cause
    attribution. Times are engine-clock seconds (the soak bench feeds a
    simulated clock); ``None`` until the phase happens."""

    episode_id: int
    cluster: str
    family: str
    cause: str
    t_first_signal_s: float
    t_detected_s: float
    t_fired_s: float | None = None
    t_recovered_s: float | None = None
    verb: str | None = None
    #: serving windows observed while the episode was open
    windows: int = 0

    @property
    def open(self) -> bool:
        return self.t_recovered_s is None

    @property
    def time_to_detect_s(self) -> float:
        return max(self.t_detected_s - self.t_first_signal_s, 0.0)

    @property
    def time_to_heal_s(self) -> float | None:
        """First violating signal -> first verified-clean window."""
        if self.t_recovered_s is None:
            return None
        return max(self.t_recovered_s - self.t_first_signal_s, 0.0)

    def to_json(self) -> dict:
        tth = self.time_to_heal_s
        return {
            "episode": self.episode_id,
            "cluster": self.cluster,
            "family": self.family,
            "cause": self.cause,
            "detectedS": round(self.t_detected_s, 3),
            "firedS": (
                None if self.t_fired_s is None else round(self.t_fired_s, 3)
            ),
            "recoveredS": (
                None if self.t_recovered_s is None
                else round(self.t_recovered_s, 3)
            ),
            "verb": self.verb,
            "windows": self.windows,
            "timeToDetectS": round(self.time_to_detect_s, 3),
            "timeToHealS": None if tth is None else round(tth, 3),
            "open": self.open,
        }


class SloEngine:
    """Sliding-window objective accounting + the healing-episode ledger.

    Not thread-safe by itself: callers (the stream detector, the soak
    bench) serialize observations per process — the same contract as the
    convergence taps."""

    #: closed episodes retained for the observability timeline
    EPISODE_LIMIT = 256

    def __init__(self, objectives: SloObjectives | None = None) -> None:
        self.objectives = objectives or SloObjectives()
        #: cluster -> objective -> (short window, long window)
        self._windows: dict[str, dict[str, tuple[_BoolWindow, _BoolWindow]]] = {}
        #: cluster -> objective -> (good, total) over the WHOLE run — the
        #: soak bench's compliance gate reads this, not the sliding pair
        self._totals: dict[str, dict[str, list[int]]] = {}
        self._episode_ids = itertools.count(1)
        #: open episodes, keyed by cluster (one verb per episode — a
        #: persistent violation must not storm the facade with verbs)
        self._open: dict[str, HealingEpisode] = {}
        self._closed: collections.deque = collections.deque(
            maxlen=self.EPISODE_LIMIT
        )

    # ----- window accounting ------------------------------------------------

    def _cluster_windows(self, cluster: str):
        w = self._windows.get(cluster)
        if w is None:
            o = self.objectives
            w = self._windows[cluster] = {
                obj: (
                    _BoolWindow(o.short_windows),
                    _BoolWindow(o.long_windows),
                )
                for obj in OBJECTIVES
            }
            self._totals[cluster] = {obj: [0, 0] for obj in OBJECTIVES}
        return w

    def observe(self, cluster: str, *, warm: bool, verified: bool,
                wall_s: float | None, violation_free: bool = True) -> dict:
        """Account one serving window; returns the per-objective goodness
        booleans (the detector reuses them for episode recovery)."""
        good = {
            "warm_served": bool(warm and verified),
            "latency": (
                wall_s is not None
                and wall_s <= self.objectives.latency_budget_s
            ),
            "violation_free": bool(violation_free),
        }
        w = self._cluster_windows(cluster)
        totals = self._totals[cluster]
        for obj, ok in good.items():
            short, long_ = w[obj]
            short.add(ok)
            long_.add(ok)
            totals[obj][0] += int(ok)
            totals[obj][1] += 1
        ep = self._open.get(cluster)
        if ep is not None:
            ep.windows += 1
        return good

    def burn_rates(self, cluster: str | None = None) -> dict:
        """objective -> {short, long} burn rates (error rate over error
        budget; None before any observation). ``cluster=None`` returns
        the worst burn across clusters per objective — the paging view."""
        clusters = (
            [cluster] if cluster is not None else list(self._windows)
        )
        out: dict = {}
        for obj in OBJECTIVES:
            budget = max(1.0 - self.objectives.target(obj), 1e-9)
            short_burn = long_burn = None
            for cid in clusters:
                w = self._windows.get(cid)
                if w is None:
                    continue
                short, long_ = w[obj]
                se, le = short.error_rate(), long_.error_rate()
                if se is not None:
                    b = se / budget
                    short_burn = b if short_burn is None else max(short_burn, b)
                if le is not None:
                    b = le / budget
                    long_burn = b if long_burn is None else max(long_burn, b)
            out[obj] = {"short": short_burn, "long": long_burn}
        return out

    def compliance(self, cluster: str | None = None) -> dict:
        """objective -> {good, total, fraction, target, met} over the
        whole run (aggregated across clusters when ``cluster`` is None)."""
        clusters = (
            [cluster] if cluster is not None else list(self._totals)
        )
        out: dict = {}
        for obj in OBJECTIVES:
            good = total = 0
            for cid in clusters:
                t = self._totals.get(cid)
                if t is None:
                    continue
                good += t[obj][0]
                total += t[obj][1]
            frac = (good / total) if total else None
            target = self.objectives.target(obj)
            out[obj] = {
                "good": good, "total": total,
                "fraction": None if frac is None else round(frac, 4),
                "target": target,
                "met": bool(frac is None or frac >= target),
            }
        return out

    # ----- healing episodes -------------------------------------------------

    def open_episode(self, cluster: str, family: str, cause: str,
                     t_first_signal_s: float,
                     t_detected_s: float) -> HealingEpisode | None:
        """Open a healing episode for ``cluster`` — returns the new
        episode, or None when one is already open (one verb per episode:
        the caller must NOT fire another verb)."""
        if cluster in self._open:
            return None
        ep = HealingEpisode(
            episode_id=next(self._episode_ids),
            cluster=cluster, family=family, cause=cause,
            t_first_signal_s=float(t_first_signal_s),
            t_detected_s=float(t_detected_s),
        )
        self._open[cluster] = ep
        return ep

    def episode(self, cluster: str) -> HealingEpisode | None:
        return self._open.get(cluster)

    def mark_fired(self, cluster: str, verb: str, t_s: float) -> None:
        ep = self._open.get(cluster)
        if ep is not None and ep.t_fired_s is None:
            ep.t_fired_s = float(t_s)
            ep.verb = verb

    def mark_recovered(self, cluster: str, t_s: float) -> HealingEpisode | None:
        """Close the cluster's open episode at the FIRST verified-clean
        window time ``t_s``; returns the closed episode."""
        ep = self._open.pop(cluster, None)
        if ep is None:
            return None
        ep.t_recovered_s = float(t_s)
        self._closed.append(ep)
        return ep

    def abandon(self, cluster: str) -> HealingEpisode | None:
        """Drop an open episode WITHOUT a recovery (kept out of the
        time-to-heal distribution; the soak gate counts it unrecovered)."""
        ep = self._open.pop(cluster, None)
        if ep is not None:
            self._closed.append(ep)
        return ep

    @property
    def open_episodes(self) -> list[HealingEpisode]:
        return list(self._open.values())

    @property
    def closed_episodes(self) -> list[HealingEpisode]:
        return list(self._closed)

    def times_to_heal(self) -> list[float]:
        return [
            ep.time_to_heal_s for ep in self._closed
            if ep.time_to_heal_s is not None
        ]

    def episodes_json(self, limit: int = 32) -> list[dict]:
        """Newest-last episode timeline (closed then open), bounded."""
        eps = list(self._closed)[-limit:] + list(self._open.values())
        return [ep.to_json() for ep in eps[-limit:]]

    # ----- observability ----------------------------------------------------

    def summary(self) -> dict:
        """VIEWER-safe summary for ``AnalyzerState.observability``: pure
        numbers and family names — no recorder paths, no stacks, no
        per-window detail."""
        tth = self.times_to_heal()
        return {
            "objectives": {
                "windowSeconds": self.objectives.window_s,
                "shortWindows": self.objectives.short_windows,
                "longWindows": self.objectives.long_windows,
                "warmTarget": self.objectives.warm_target,
                "latencyBudgetSeconds": self.objectives.latency_budget_s,
                "latencyTarget": self.objectives.latency_target,
                "dwellTarget": self.objectives.dwell_target,
            },
            "burnRates": self.burn_rates(),
            "compliance": self.compliance(),
            "episodes": {
                "open": len(self._open),
                "closed": len(self._closed),
                "recovered": len(tth),
                "timeToHealP50S": percentile(tth, 0.50),
                "timeToHealP99S": percentile(tth, 0.99),
            },
        }
