"""Framework exceptions (ref: com.linkedin.kafka.cruisecontrol.exception)."""

from __future__ import annotations


class CruiseControlException(Exception):
    """Root (ref KafkaCruiseControlException)."""


class NotEnoughValidWindowsException(CruiseControlException):
    """Monitor completeness below the request's requirements (ref C8)."""


class OptimizationFailureException(CruiseControlException):
    """A hard goal cannot be satisfied (ref C16)."""


class OngoingExecutionException(CruiseControlException):
    """An execution is already in progress (ref Executor reservation)."""


class UserRequestException(CruiseControlException):
    """Bad request parameters (servlet 400s, ref C32)."""
