"""Device cost observatory — XLA cost/memory accounting + roofline
projections for every compiled program (ISSUE 6 tentpole).

The flight recorder (``ccx.common.tracing``) says *where* a run was when it
died; this module says *what the compiled programs cost*. Each jitted
engine program (SA chunk, polish/swap-polish chunks, repair loop, stack
eval, aggregates — see the ``instrument`` call sites) is wrapped so that:

* every invocation is **counted** per (program label, argument-shape
  signature) — a few tree-flatten attribute reads, no jax arrays touched,
  so counting can never perturb program shapes or cost a warm rung a
  recompile (pinned by tests/test_costmodel.py);
* the first invocation of a new shape **enqueues a capture spec**
  (``jax.ShapeDtypeStruct`` skeletons — the arrays themselves are never
  retained); an explicit ``capture_pending()`` flush (the optimizer's
  ``cost-capture`` phase, i.e. the bench prewarm-ledger seam and the
  sidecar's compile path) then AOT-lowers and compiles each spec and
  records ``compiled.cost_analysis()`` + ``compiled.memory_analysis()``:
  per-program FLOPs, bytes accessed, argument/output/temp HBM.

Capture is OFF by default (``set_capture`` / env ``CCX_COST_CAPTURE`` /
config ``observability.cost.capture``): the AOT compile of an
already-jitted program is one extra backend compile per program shape —
served by the persistent compile cache when one is armed (bench always
arms ``.jax_cache/``), charged to the ``costmodel:<label>`` compilestats
attribution either way, and paid on the COLD path only. A warm run never
captures (the shape key is already in the ledger), which is what the
zero-warm-fresh-compile tripwire pins.

Graceful degradation is the contract: CPU and TPU backends expose
different ``cost_analysis`` key sets (CPU returns a list of per-partition
dicts with ``flops``/``bytes accessed``; TPU may omit either or raise for
helper executables) — a missing field records ``None``, an analysis
failure records the error string, and nothing here ever raises into the
optimizer.

From the captured numbers plus a small device-spec table (v5e/v5p/v4
peak FLOP/s + HBM GB/s, CPU host estimates; override via
``observability.cost.peak.tflops`` / ``observability.cost.hbm.gbps``),
``projection()`` computes roofline times — ``max(flops/peak,
bytes/bandwidth)`` per call — per program and per device. One honesty
caveat is handled explicitly: XLA's cost analysis counts a while/scan
body ONCE, so call sites whose loop trip count is static program shape
declare it via ``instrument(label, iters=...)`` and projections scale by
it; traced-budget while_loops stay at 1 and their projections are
explicit floors. The per-phase
rollup rides ``OptimizerResult.costModel`` (BENCH lines, the sidecar
result — VOLATILE in golden fixtures), the span tree (each phase span
carries its executed programs' projected device seconds and HBM
watermark), ``GET /observability`` and Prometheus gauges;
``tools/bench_ledger.py --roofline`` renders it as the budget table that
replaces the hand-summed one in docs/perf-notes.md.
"""

from __future__ import annotations

import hashlib
import threading
import time

#: env switch for capture arming (config ``observability.cost.capture``
#: takes precedence when a facade is constructed; bench/tools use the env)
ENV_CAPTURE = "CCX_COST_CAPTURE"

#: Device-spec table: peak dense FLOP/s (bf16 MXU peak for TPUs — the
#: roofline ceiling XLA schedules against; the engine's f32 element-wise
#: work runs below it, so projections are LOWER bounds) and HBM bytes/s.
#: Sources: published v5e/v5p/v4 chip specs. The CPU row is an honest
#: order-of-magnitude host estimate (few-GHz core × SIMD width, DDR
#: stream bandwidth) — marked ``estimate`` and overridable.
#: ``hbmBytes`` is the per-chip memory CAPACITY (HBM; host RAM estimate on
#: the CPU row) — the ceiling the fleet snapshot registry budgets device
#: residency against (capacity minus the captured working-set watermark).
DEVICE_SPECS = {
    "cpu": {"peakFlops": 5.0e10, "hbmBytesPerSec": 2.0e10,
            "hbmBytes": 8.0e9, "estimate": True},
    "tpu-v5e": {"peakFlops": 1.97e14, "hbmBytesPerSec": 8.19e11,
                "hbmBytes": 1.6e10},
    "tpu-v5p": {"peakFlops": 4.59e14, "hbmBytesPerSec": 2.765e12,
                "hbmBytes": 9.5e10},
    "tpu-v4": {"peakFlops": 2.75e14, "hbmBytesPerSec": 1.228e12,
               "hbmBytes": 3.2e10},
}

#: device_kind substring -> spec key (first match wins, order matters:
#: "v5 lite"/"v5e" must be tested before the bare "v5" of "v5p")
_KIND_MATCHES = (
    ("v5 lite", "tpu-v5e"),
    ("v5e", "tpu-v5e"),
    ("v5p", "tpu-v5p"),
    ("v4", "tpu-v4"),
    ("cpu", "cpu"),
)

_LOCK = threading.Lock()
#: shape key -> cumulative invocation count (always on — the per-phase
#: attribution the tracing spans difference)
_CALLS: dict[str, int] = {}
#: shape key -> captured record (see ``_capture_one``)
_RECORDS: dict[str, dict] = {}
#: shape key -> (label, fn, arg specs, kwargs) awaiting capture
_PENDING: dict[str, tuple] = {}
_CAPTURE = None  # tri-state: None = follow env, else explicit bool
#: operator override of the CURRENT device's roofline ceilings
#: (observability.cost.peak.tflops / observability.cost.hbm.gbps; 0=auto)
_OVERRIDE: dict = {}
#: serializes capture flushes (compiles can be slow; the counter lock
#: must not be held across them)
_CAPTURE_LOCK = threading.Lock()


def set_capture(on: bool | None) -> None:
    """Arm/disarm capture; ``None`` restores the env default."""
    global _CAPTURE
    _CAPTURE = on if on is None else bool(on)


def capture_enabled() -> bool:
    if _CAPTURE is not None:
        return _CAPTURE
    import os

    return os.environ.get(ENV_CAPTURE) == "1"


def set_device_override(peak_tflops: float = 0.0, hbm_gbps: float = 0.0) -> None:
    """Operator roofline ceilings for the current device (config
    ``observability.cost.peak.tflops`` / ``observability.cost.hbm.gbps``);
    0 keeps the table value."""
    with _LOCK:
        _OVERRIDE.clear()
        if peak_tflops and peak_tflops > 0:
            _OVERRIDE["peakFlops"] = float(peak_tflops) * 1e12
        if hbm_gbps and hbm_gbps > 0:
            _OVERRIDE["hbmBytesPerSec"] = float(hbm_gbps) * 1e9


def reset() -> None:
    """Clear counters/ledger/pending (tests only — the ledger is
    process-global by design, like compilestats)."""
    with _LOCK:
        _CALLS.clear()
        _RECORDS.clear()
        _PENDING.clear()


# ----- instrumentation seam --------------------------------------------------


def _leaf_sig(x) -> object:
    """One leaf's contribution to the shape signature. Array-likes reduce
    to (shape, dtype) — reading ``.shape``/``.dtype`` never touches device
    data (works on donated/deleted buffers too). Hashable statics (opts
    dataclasses, goal tuples) contribute their hash; anything else its
    type name (conservative: distinct programs may share a key, which only
    means one shared cost record)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if isinstance(x, (int, float, bool, str, bytes, type(None))):
        return repr(x)
    try:
        return f"{type(x).__name__}#{hash(x)}"
    except TypeError:
        return type(x).__name__


def _spec_of(x):
    """Capture-spec leaf: ShapeDtypeStruct skeleton for array-likes (no
    buffer retained), the value itself otherwise (static kwargs, python
    scalars — ``jit.lower`` accepts both). Mesh-sharded leaves keep their
    NamedSharding on the skeleton: the AOT capture compile must lower the
    SAME SPMD program the run executed (and hit the same persistent-cache
    entry), not a single-device twin of it."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        import jax

        sharding = getattr(x, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            try:
                return jax.ShapeDtypeStruct(
                    tuple(shape), dtype, sharding=sharding
                )
            except TypeError:  # older jax: no sharding kwarg
                pass
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return x


class _Instrumented:
    """Transparent wrapper around a jitted callable: counts invocations
    per shape key and (capture armed) enqueues a one-time capture spec.
    Attribute access (``.lower``, ``.clear_cache``, …) passes through.

    ``iters``: XLA's cost analysis counts a while/scan BODY once — it
    cannot know trip counts — so a chunk program's captured FLOPs/bytes
    are per structure, not per execution. Where the trip count IS static
    program shape (the SA chunk's ``chunk``, the descent engines'
    ``chunk_iters``), the call site declares an extractor
    ``iters(kwargs) -> int`` and projections scale flops/bytes by it;
    programs without one (while_loop monoliths with traced budgets) stay
    at 1, making their projections explicit floors."""

    __slots__ = ("_fn", "_label", "_iters", "__wrapped__")

    def __init__(self, label: str, fn, iters=None) -> None:
        self._label = label
        self._fn = fn
        self._iters = iters
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        try:
            self._observe(args, kwargs)
        except Exception:  # noqa: BLE001 — accounting must never break
            pass  # the engine (degradation contract, module docstring)
        return self._fn(*args, **kwargs)

    def _observe(self, args, kwargs) -> None:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = repr((self._label, tuple(_leaf_sig(x) for x in leaves)))
        digest = hashlib.blake2b(
            (sig + str(treedef)).encode(), digest_size=6
        ).hexdigest()
        key = f"{self._label}#{digest}"
        new = False
        with _LOCK:
            n = _CALLS.get(key, 0)
            _CALLS[key] = n + 1
            new = n == 0 and key not in _RECORDS and key not in _PENDING
        if new and capture_enabled():
            spec_args, spec_kwargs = jax.tree_util.tree_map(
                _spec_of, (args, dict(kwargs))
            )
            loop_iters = 1
            if self._iters is not None:
                try:
                    loop_iters = max(int(self._iters(kwargs)), 1)
                except Exception:  # noqa: BLE001 — floor, never crash
                    loop_iters = 1
            with _LOCK:
                if key not in _RECORDS and key not in _PENDING:
                    _PENDING[key] = (
                        self._label, self._fn, spec_args, spec_kwargs,
                        loop_iters,
                    )

    def __getattr__(self, name):
        return getattr(self._fn, name)


def instrument(label: str, iters=None):
    """Decorator naming one engine program for the cost ledger:

        @costmodel.instrument("sa-chunk", iters=lambda k: k["chunk"])
        @functools.partial(jax.jit, ...)
        def _run_chunk(...): ...

    ``iters(kwargs) -> int`` declares the program's static loop trip
    count (see ``_Instrumented``: XLA costs loop bodies once)."""

    def deco(fn):
        return _Instrumented(label, fn, iters=iters)

    return deco


# ----- capture ---------------------------------------------------------------


def _normalize_cost(raw) -> tuple[dict, list[str], str | None]:
    """cost_analysis() output -> (fields, raw key list, error). Backends
    disagree on the container (CPU: list of per-partition dicts; TPU: one
    dict or None) and on the key set — absent metrics become None, never
    a crash."""
    fields = {
        "flops": None, "bytesAccessed": None, "transcendentals": None,
        "partitions": 1,
    }
    if isinstance(raw, (list, tuple)):
        # multi-partition executables return one dict per partition — sum
        # numeric metrics across partitions (keeping only partition 0
        # would silently under-report a sharded program by the partition
        # count while still claiming capture). The partition count rides
        # the record so projections can divide back down: the chips run
        # CONCURRENTLY, so per-chip roofline = global FLOPs / mesh size.
        dicts = [d for d in raw if isinstance(d, dict)]
        if len(dicts) > 1:
            merged: dict = {}
            for d in dicts:
                for k, v in d.items():
                    if isinstance(v, (int, float)):
                        merged[k] = merged.get(k, 0.0) + float(v)
            raw = merged
            fields["partitions"] = len(dicts)
        else:
            raw = dicts[0] if dicts else (raw[0] if raw else None)
    if not isinstance(raw, dict):
        return fields, [], None if raw is None else f"unexpected {type(raw).__name__}"
    for out_key, src in (
        ("flops", "flops"),
        ("bytesAccessed", "bytes accessed"),
        ("transcendentals", "transcendentals"),
    ):
        v = raw.get(src)
        if isinstance(v, (int, float)):
            fields[out_key] = float(v)
    return fields, sorted(raw.keys()), None


def _normalize_memory(stats) -> dict:
    """memory_analysis() output -> byte fields (None where the backend
    does not expose the attribute)."""
    out = {}
    for out_key, attr in (
        ("argumentBytes", "argument_size_in_bytes"),
        ("outputBytes", "output_size_in_bytes"),
        ("tempBytes", "temp_size_in_bytes"),
        ("aliasBytes", "alias_size_in_bytes"),
        ("generatedCodeBytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(stats, attr, None)
        out[out_key] = float(v) if isinstance(v, (int, float)) else None
    # peak resident HBM while the program runs: arguments + outputs +
    # scratch, minus donated (aliased) buffers counted on both sides
    known = [out[k] for k in ("argumentBytes", "outputBytes", "tempBytes")]
    if any(v is not None for v in known):
        peak = sum(v for v in known if v is not None)
        if out["aliasBytes"] is not None:
            peak -= out["aliasBytes"]
        out["peakBytes"] = max(peak, 0.0)
    else:
        out["peakBytes"] = None
    return out


def _capture_one(key: str, label: str, fn, spec_args, spec_kwargs,
                 loop_iters: int = 1) -> dict:
    rec: dict = {
        "label": label, "key": key,
        "flops": None, "bytesAccessed": None, "transcendentals": None,
        "partitions": 1,
        "argumentBytes": None, "outputBytes": None, "tempBytes": None,
        "aliasBytes": None, "generatedCodeBytes": None, "peakBytes": None,
        # declared static loop trip count (projections scale flops/bytes
        # by it — XLA cost analysis counts a loop body once); 1 = none
        # declared, the projection is a floor
        "loopIters": max(int(loop_iters), 1),
        "costKeys": [], "error": None,
    }
    t0 = time.monotonic()
    try:
        from ccx.common import compilestats

        with compilestats.attributed(f"costmodel:{label}"):
            compiled = fn.lower(*spec_args, **spec_kwargs).compile()
        try:
            fields, keys, err = _normalize_cost(compiled.cost_analysis())
            rec.update(fields)
            rec["costKeys"] = keys
            if err:
                rec["error"] = f"cost_analysis: {err}"
        except Exception as e:  # noqa: BLE001 — degradation contract
            rec["error"] = f"cost_analysis: {e}"
        try:
            rec.update(_normalize_memory(compiled.memory_analysis()))
        except Exception as e:  # noqa: BLE001
            rec["error"] = (
                (rec["error"] + "; " if rec["error"] else "")
                + f"memory_analysis: {e}"
            )
    except Exception as e:  # noqa: BLE001 — lower/compile itself failed
        rec["error"] = f"lower/compile: {e}"
    rec["captureSeconds"] = round(time.monotonic() - t0, 3)
    return rec


def capture_pending() -> int:
    """Flush the pending-capture queue: AOT lower+compile each enqueued
    shape spec and record its cost/memory analyses. Returns the number of
    programs captured. The optimizer calls this from its ``cost-capture``
    phase (cold path only — a warm run enqueues nothing); compile cost is
    charged to ``costmodel:<label>`` attribution and served by the
    persistent compile cache when armed. Never raises."""
    with _CAPTURE_LOCK:
        with _LOCK:
            pending = dict(_PENDING)
            _PENDING.clear()
        n = 0
        for key, (label, fn, spec_args, spec_kwargs, iters) in pending.items():
            rec = _capture_one(key, label, fn, spec_args, spec_kwargs, iters)
            with _LOCK:
                _RECORDS[key] = rec
            n += 1
        return n


def pending_count() -> int:
    with _LOCK:
        return len(_PENDING)


def records() -> dict[str, dict]:
    """The captured ledger (key -> record), a copy."""
    with _LOCK:
        return {k: dict(v) for k, v in _RECORDS.items()}


# ----- execution counters ----------------------------------------------------


def exec_snapshot() -> dict[str, int]:
    """Cumulative per-shape-key invocation counts (cheap dict copy — the
    tracing spans snapshot this at start/end, like compilestats)."""
    with _LOCK:
        return dict(_CALLS)


def exec_delta(before: dict[str, int]) -> dict[str, int]:
    """Invocations since ``before`` (keys with a positive delta only)."""
    now = exec_snapshot()
    return {
        k: n - before.get(k, 0) for k, n in now.items() if n > before.get(k, 0)
    }


# ----- roofline --------------------------------------------------------------


def device_kind() -> str:
    """The current backend's device kind string ('cpu', 'TPU v5 lite', …);
    'unknown' when jax is unusable."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        return "unknown"


def spec_for(kind: str) -> dict | None:
    """Device-spec row for a device_kind string (None = not in the table:
    projections for the live device degrade to null, the fixed-table
    projections below still apply)."""
    k = kind.lower()
    for needle, spec_key in _KIND_MATCHES:
        if needle in k:
            return {"key": spec_key, **DEVICE_SPECS[spec_key]}
    return None


def device_spec() -> dict:
    """The CURRENT device's roofline ceilings: table row matched on
    device_kind, operator overrides applied on top."""
    kind = device_kind()
    spec = spec_for(kind) or {"key": None, "peakFlops": None, "hbmBytesPerSec": None}
    out = {"deviceKind": kind, **spec}
    with _LOCK:
        override = dict(_OVERRIDE)
    if override:
        out.update(override)
        out["source"] = "override"
    else:
        out["source"] = "table" if spec.get("key") else "unknown"
    return out


def hbm_watermark_bytes() -> float:
    """The captured working-set watermark: max ``peakBytes`` over every
    program record in the ledger — what the engine programs themselves
    need live in HBM at peak. The fleet snapshot registry prices its
    device-residency budget as capacity minus THIS (a snapshot kept
    resident must never evict the working set the next chunk needs).
    0.0 when nothing is captured yet (cold process)."""
    with _LOCK:
        recs = list(_RECORDS.values())
    peaks = [
        r["peakBytes"] for r in recs
        if isinstance(r.get("peakBytes"), (int, float))
    ]
    return float(max(peaks)) if peaks else 0.0


#: config-layer override of the fleet snapshot budget (facade wires
#: ``optimizer.fleet.snapshot.hbm.mb`` here; 0/None = no override)
_FLEET_HBM_MB: float | None = None


def set_fleet_hbm_budget(mb: float | None) -> None:
    """Config hook (``optimizer.fleet.snapshot.hbm.mb``): 0/None restores
    the auto budget."""
    global _FLEET_HBM_MB
    _FLEET_HBM_MB = float(mb) if mb else None


def fleet_snapshot_budget_bytes(explicit_mb: float | None = None) -> int:
    """HBM budget for device-resident fleet snapshots
    (ccx.sidecar.server.SnapshotRegistry): an explicit operator setting
    (constructor arg, ``optimizer.fleet.snapshot.hbm.mb`` via the config
    hook, or CCX_FLEET_HBM_MB) wins; else half of (device HBM capacity −
    captured watermark) — half, because the optimizer also holds
    transient copies (donated carries, diff buffers) the watermark
    undercounts on a cold ledger. Floor of 64 MB so a pathological
    watermark can never disable the registry outright."""
    import os

    if explicit_mb is None:
        explicit_mb = _FLEET_HBM_MB
    if explicit_mb is None:
        env = os.environ.get("CCX_FLEET_HBM_MB")
        explicit_mb = float(env) if env else None
    if explicit_mb is not None and explicit_mb > 0:
        return int(explicit_mb * 1e6)
    cap = device_spec().get("hbmBytes") or DEVICE_SPECS["cpu"]["hbmBytes"]
    budget = (float(cap) - hbm_watermark_bytes()) / 2.0
    return int(max(budget, 64e6))


def roofline_seconds(flops, bytes_accessed, spec: dict):
    """max(flops/peak, bytes/bandwidth) — None when neither input or no
    ceiling is known. Returns (seconds, bound) with bound one of
    'compute'/'memory'/None."""
    t_c = (
        flops / spec["peakFlops"]
        if flops is not None and spec.get("peakFlops")
        else None
    )
    t_m = (
        bytes_accessed / spec["hbmBytesPerSec"]
        if bytes_accessed is not None and spec.get("hbmBytesPerSec")
        else None
    )
    if t_c is None and t_m is None:
        return None, None
    if t_m is None:
        return t_c, "compute"
    if t_c is None:
        return t_m, "memory"
    return (t_m, "memory") if t_m >= t_c else (t_c, "compute")


# ----- projections -----------------------------------------------------------


def _round(v, nd=6):
    return None if v is None else round(v, nd)


def projection(delta: dict[str, int], specs: dict[str, dict] | None = None) -> dict:
    """Roll an execution delta (shape key -> calls) up against the ledger:
    per-program-label totals, roofline seconds per device spec, HBM
    watermark, and coverage (calls whose program has no captured record
    yet — the cold-run case — are counted, never guessed at)."""
    if specs is None:
        specs = {"device": device_spec()}
    with _LOCK:
        recs = {k: _RECORDS.get(k) for k in delta}
    programs: dict[str, dict] = {}
    totals = {"calls": 0, "flops": 0.0, "bytesAccessed": 0.0}
    # per-chip roofline inputs: a mesh-sharded program's captured
    # FLOPs/bytes are GLOBAL sums over its partitions (``_normalize_cost``
    # merges the per-partition dicts), but the chips execute concurrently,
    # so projected wall time divides by the partition count — per-chip
    # roofline = global FLOPs / mesh size. Totals stay global (honest
    # work accounting); only the time projections use the per-chip view.
    chip = {"flops": 0.0, "bytesAccessed": 0.0}
    any_flops = any_bytes = False
    peak = None
    uncaptured_calls = 0
    captured_programs = 0
    for key, calls in delta.items():
        rec = recs.get(key)
        label = key.rsplit("#", 1)[0]
        slot = programs.setdefault(
            label,
            {"calls": 0, "flops": None, "bytesAccessed": None,
             "hbmPeakBytes": None, "captured": False, "partitions": 1},
        )
        slot["calls"] += calls
        totals["calls"] += calls
        if rec is None:
            uncaptured_calls += calls
            continue
        captured_programs += 1
        slot["captured"] = True
        parts = max(int(rec.get("partitions") or 1), 1)
        slot["partitions"] = max(slot["partitions"], parts)
        # flops/bytes scale by call count AND the declared static loop
        # trip count (XLA costs a loop body once — _Instrumented.iters);
        # the HBM watermark does NOT scale with iterations
        mult = calls * rec.get("loopIters", 1)
        if rec["flops"] is not None:
            slot["flops"] = (slot["flops"] or 0.0) + rec["flops"] * mult
            totals["flops"] += rec["flops"] * mult
            chip["flops"] += rec["flops"] * mult / parts
            any_flops = True
        if rec["bytesAccessed"] is not None:
            slot["bytesAccessed"] = (
                (slot["bytesAccessed"] or 0.0) + rec["bytesAccessed"] * mult
            )
            totals["bytesAccessed"] += rec["bytesAccessed"] * mult
            chip["bytesAccessed"] += rec["bytesAccessed"] * mult / parts
            any_bytes = True
        if rec["peakBytes"] is not None:
            slot["hbmPeakBytes"] = max(slot["hbmPeakBytes"] or 0.0, rec["peakBytes"])
            peak = max(peak or 0.0, rec["peakBytes"])
    if not any_flops:
        totals["flops"] = None
        chip["flops"] = None
    if not any_bytes:
        totals["bytesAccessed"] = None
        chip["bytesAccessed"] = None
    proj = {}
    for name, spec in specs.items():
        secs, bound = roofline_seconds(
            chip["flops"], chip["bytesAccessed"], spec
        )
        proj[name] = {"seconds": _round(secs), "bound": bound}
    for slot in programs.values():
        sparts = max(slot.pop("partitions", 1), 1)
        sf = None if slot["flops"] is None else slot["flops"] / sparts
        sb = (
            None if slot["bytesAccessed"] is None
            else slot["bytesAccessed"] / sparts
        )
        if sparts > 1:
            slot["partitions"] = sparts
        slot["projectedSeconds"] = {
            name: _round(roofline_seconds(sf, sb, spec)[0])
            for name, spec in specs.items()
        }
    return {
        "totals": {**totals, "hbmPeakBytes": peak},
        "projected": proj,
        "programs": programs,
        "coverage": {
            "programsExecuted": len(delta),
            "programsCaptured": captured_programs,
            "callsUncaptured": uncaptured_calls,
        },
    }


def projection_compact(delta: dict[str, int]) -> dict | None:
    """The span-sized rollup a phase span carries (ccx.common.tracing):
    projected device seconds on the CURRENT device, HBM watermark, call
    counts. None when the delta is empty (host-only phases)."""
    if not delta:
        return None
    p = projection(delta)
    dev = p["projected"].get("device", {})
    out = {
        "calls": p["totals"]["calls"],
        # raw counters ride along so downstream consumers (the bench
        # ledger's --roofline table) can re-project onto OTHER device
        # specs without the per-program ledger
        "flops": p["totals"]["flops"],
        "bytesAccessed": p["totals"]["bytesAccessed"],
        "projectedSeconds": dev.get("seconds"),
        "bound": dev.get("bound"),
        "hbmPeakBytes": p["totals"]["hbmPeakBytes"],
    }
    unc = p["coverage"]["callsUncaptured"]
    if unc:
        out["callsUncaptured"] = unc
    return out


#: the fixed projection targets every costModel block carries next to the
#: live device: the T1 chase device (v5e) and the scale-up part (v5p)
PROJECTION_TARGETS = ("tpu-v5e", "tpu-v5p")


def _spec_table() -> dict[str, dict]:
    specs = {"device": device_spec()}
    for key in PROJECTION_TARGETS:
        specs[key] = {"key": key, **DEVICE_SPECS[key]}
    return specs


def cost_model_json(delta: dict[str, int], span_tree: dict | None = None) -> dict:
    """The ``OptimizerResult.costModel`` block: device spec + roofline
    projections (live device and the fixed v5e/v5p targets) rolled up per
    program and per phase. Per-phase rows come from the span tree's phase
    children (each phase span carries its own exec-delta rollup).
    VOLATILE in golden wire fixtures — machine-dependent by construction."""
    specs = _spec_table()
    p = projection(delta, specs=specs)
    phases = {}
    for child in (span_tree or {}).get("children", ()):
        if child.get("kind") == "phase" and child.get("costModel"):
            phases[child["name"]] = child["costModel"]
    return {
        "device": specs["device"],
        "totals": p["totals"],
        "projected": p["projected"],
        "programs": p["programs"],
        "coverage": p["coverage"],
        **({"phases": phases} if phases else {}),
    }


# ----- export ----------------------------------------------------------------


def summary() -> dict:
    """Ledger view for ``GET /observability``: capture state, captured
    records, live call totals."""
    with _LOCK:
        recs = {k: dict(v) for k, v in _RECORDS.items()}
        calls = dict(_CALLS)
        pending = len(_PENDING)
    return {
        "captureEnabled": capture_enabled(),
        "device": device_spec(),
        "programsSeen": len(calls),
        "programsCaptured": len(recs),
        "programsPending": pending,
        "records": recs,
        "calls": calls,
    }


def export_gauges(registry=None) -> None:
    """Cost-observatory gauges for /metrics (idempotent, like
    ``compilestats.export_gauges``): captured/pending program counts and
    the cumulative projected device seconds of everything executed so far
    — a projected-seconds gauge far below wall-clock under a flat
    heartbeat is the 'host-bound, not device-bound' signature."""
    if registry is None:
        from ccx.common.metrics import REGISTRY as registry  # noqa: N811

    def _projected_total() -> float:
        with _LOCK:
            calls = dict(_CALLS)
        p = projection(calls)
        dev = p["projected"].get("device", {})
        return float(dev.get("seconds") or 0.0)

    registry.gauge(
        "cost-programs-captured",
        lambda: float(len(_RECORDS)),
        help="program shapes with a captured XLA cost/memory record",
    )
    registry.gauge(
        "cost-programs-pending",
        lambda: float(pending_count()),
        help="program shapes enqueued for cost capture",
    )
    registry.gauge(
        "cost-projected-device-seconds",
        _projected_total,
        help="roofline-projected device seconds of all instrumented "
        "program executions so far (current device spec)",
    )
