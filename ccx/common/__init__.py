from ccx.common.resources import Resource, NUM_RESOURCES  # noqa: F401
