"""Resource axes tracked per replica / broker.

Parity: ``common/Resource.java`` in the reference (SURVEY.md C3) defines
CPU, NW_IN, NW_OUT, DISK with per-resource balancability and host/broker
scope. Here a resource is simply an axis index into the leading dimension of
the load tensors (float32[NUM_RESOURCES, ...]) so every goal kernel can
slice its resource without branching.
"""

from __future__ import annotations

import enum


class Resource(enum.IntEnum):
    """Index into the resource axis of load/capacity tensors."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        # Reference: CPU and NW are host-level resources; DISK is broker-level.
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)


NUM_RESOURCES = len(Resource)

#: Default capacity-utilization thresholds, keyed by resource.
#: Parity: AnalyzerConfig `cpu.capacity.threshold` (0.7),
#: `disk.capacity.threshold` (0.8), `network.inbound/outbound.capacity.threshold`
#: (0.8). (unverified against /root/reference — SURVEY.md provenance banner.)
DEFAULT_CAPACITY_THRESHOLD = {
    Resource.CPU: 0.7,
    Resource.NW_IN: 0.8,
    Resource.NW_OUT: 0.8,
    Resource.DISK: 0.8,
}

#: Default balance thresholds for resource-usage distribution goals.
#: Parity: AnalyzerConfig `*.balance.threshold` default 1.1 — a broker is
#: balanced when its utilization lies within [avg*(2-t), avg*t].
DEFAULT_BALANCE_THRESHOLD = {
    Resource.CPU: 1.1,
    Resource.NW_IN: 1.1,
    Resource.NW_OUT: 1.1,
    Resource.DISK: 1.1,
}
