"""Accelerator liveness safeguard shared by the long-running entry points.

The axon TPU tunnel can wedge such that ``jax.devices()`` hangs forever in
ANY process (docs/perf-notes.md wedge etiology). A service or sidecar that
initializes the backend lazily would boot, serve its first status endpoint,
and then hang every optimizer call — a hung service instead of a degraded
one. ``ensure_responsive_backend()`` is called before first backend use by
``python -m ccx`` (service) and ``python -m ccx.sidecar.server``:

* ``CCX_JAX_PLATFORM`` set -> apply it (the operator escape hatch; plain
  ``JAX_PLATFORMS`` is ignored because sitecustomize preloads jax) and skip
  the probe;
* otherwise probe ``jax.devices()`` in a SUBPROCESS with a timeout
  (``CCX_DEVICE_PROBE_TIMEOUT`` seconds, default 60, 0/invalid-value-safe);
  on rc!=0 or timeout, force the CPU platform and log a warning.

The probe child is SIGTERMed with grace and only then killed — killing a
client outright mid device claim is what CAUSES the wedge — and reaping is
bounded so a child stuck in uninterruptible device I/O can never hang the
caller. Mirrors bench.py's probe (the reference pattern).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys

log = logging.getLogger(__name__)


def probe_devices(timeout_s: int, capture_stdout: bool = False):
    """Probe ``jax.devices()`` in a subprocess with the wedge-safe reap
    ladder. Returns ``(rc, stdout)``: rc is the child's exit code or None
    on timeout; stdout is the captured device listing ("" unless
    ``capture_stdout``). This is the ONE implementation of the
    SIGTERM-grace-then-kill discipline — bench.py and the service/sidecar
    entry points all route through it, so etiology learnings land once."""
    probe = subprocess.Popen(
        [sys.executable, "-c", "import jax; print(jax.devices())"],
        stdout=subprocess.PIPE if capture_stdout else subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    rc: int | None
    out = ""
    try:
        # communicate() drains the pipe concurrently — wait() + read-after
        # would deadlock a child whose output exceeds the OS pipe buffer
        # (misclassifying a healthy device as wedged)
        out, _ = probe.communicate(timeout=timeout_s)
        out = out or ""
        rc = probe.returncode
    except subprocess.TimeoutExpired:
        rc = None
    finally:
        if probe.poll() is None:
            probe.terminate()
            try:
                probe.wait(timeout=15)
            except subprocess.TimeoutExpired:
                probe.kill()
                try:
                    # a child stuck in uninterruptible device I/O can
                    # survive SIGKILL — never let reaping block the caller
                    probe.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
    return rc, out


def ensure_responsive_backend(timeout_s: int | None = None) -> bool:
    """Apply CCX_JAX_PLATFORM or probe the accelerator; force CPU on
    failure. Returns True when the configured/probed backend is usable
    without forcing a fallback."""
    forced = os.environ.get("CCX_JAX_PLATFORM")
    if forced:
        import jax

        jax.config.update("jax_platforms", forced)
        log.info("jax platform forced to %s (CCX_JAX_PLATFORM)", forced)
        return True

    if timeout_s is None:
        raw = os.environ.get("CCX_DEVICE_PROBE_TIMEOUT", "60")
        try:
            timeout_s = int(raw)
        except ValueError:
            log.warning(
                "CCX_DEVICE_PROBE_TIMEOUT=%r is not an integer; using 60",
                raw,
            )
            timeout_s = 60
        if timeout_s < 0:
            # only an explicit 0 disables the safeguard — a negative value
            # is a typo/templating bug, not a request to run unprotected
            log.warning(
                "CCX_DEVICE_PROBE_TIMEOUT=%s is negative; using 60", timeout_s
            )
            timeout_s = 60
    if timeout_s == 0:
        return True

    rc, _ = probe_devices(timeout_s)
    if rc == 0:
        return True
    reason = (
        "device probe timed out — accelerator wedged?"
        if rc is None
        else f"device probe rc={rc}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    log.warning("%s; optimizer falling back to the CPU backend", reason)
    return False
