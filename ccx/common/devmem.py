"""Unified device-memory manager — one ledger for all device residency.

Rounds 12 and 14 each grew their own device cache: the fleet's
``SnapshotRegistry`` (``ccx/sidecar/server.py``) LRU-evicts built device
models under a costmodel-priced HBM budget, while the incremental loop's
``PlacementStore`` (``ccx/search/incremental.py``) kept warm placement
bases under a COUNT cap (``max_sessions``) that sat entirely outside that
budget — the stale-docs wart "Integrative Dynamic Reconfiguration"
(PAPERS.md, 1602.03770) warns about: coupled resources managed by
independent policies fight each other exactly when memory is tight. This
module is the one allocator both ride (and "Tetris", PAPERS.md
2508.00426, is the exemplar: admission/eviction as packing under
per-resource capacity):

* every device-resident object is an **entry**: a ``(class, key)`` pair
  with a byte size, a priority, an LRU stamp and an eviction callback
  supplied by the owning cache. Classes today: ``snapshot`` (built
  device cluster models), ``warmBase`` (converged placement bases +
  pressure banks), ``program`` (compiled-program working set — the cost
  observatory's captured HBM watermark, pinned: XLA owns that memory,
  the ledger only *accounts* it);
* admission is **priority-aware packing**: when the evictable classes
  (snapshots + warm bases) exceed the budget, victims are chosen lowest
  priority first, LRU within a priority — and an admission may NEVER
  evict an entry of strictly higher priority, so an urgent self-healing
  job's warm base or snapshot cannot be displaced by a dryrun
  (priority 10 vs 0, the fleet scheduler's vocabulary). An entry's
  priority is the priority of the LAST job that used it — a later
  dryrun touch demotes it back, so completed urgent jobs do not pin
  memory forever;
* eviction is **never an error** by construction: the owning caches
  registered callbacks that drop only the device copy — an evicted
  snapshot rebuilds from host arrays on its next Propose, an evicted
  warm base degrades to the documented ``ColdStartRequired`` cold start
  (reason on the result, the RPC succeeds);
* when no permissible victim exists (everything live is higher
  priority, or a single entry alone exceeds the budget) the admission
  still proceeds and is counted (``overBudgetAdmissions``) — serving
  beats strict accounting, one job must always be able to run (the
  SnapshotRegistry's original contract, now ledger-wide).

The budget is the costmodel-derived HBM budget
(``ccx.common.costmodel.fleet_snapshot_budget_bytes``: explicit operator
setting, else half of device capacity minus the captured program
watermark — the watermark is the same number the pinned ``program``
entry reports, so programs are priced exactly once). The config key
``optimizer.devmem.budget.mb`` (and env ``CCX_DEVMEM_BUDGET_MB``)
overrides it for the unified ledger specifically.

Everything is observable: resident bytes per class and eviction counts
by (reason, priority) ride ``GET /observability``,
``AnalyzerState.observability.deviceMemory`` and labeled Prometheus
gauges (``ccx_devmem_resident_bytes{class=...}``,
``ccx_devmem_evictions{reason=...,priority=...}`` — strict-exposition-
parser-safe), and ``bench.py --steady-fleet`` samples the ledger every
window to prove the fleet never exceeds the budget.

Import-light on purpose (stdlib only at module load): the scheduler and
the incremental store import this at their own import time.
"""

from __future__ import annotations

import os
import threading

#: entry classes whose bytes the ledger may reclaim. ``program`` is
#: accounted but pinned — the compiled working set belongs to XLA and is
#: already subtracted from the auto-derived budget (costmodel watermark).
EVICTABLE_CLASSES = frozenset({"snapshot", "warmBase"})

#: env twin of ``optimizer.devmem.budget.mb`` (0/unset = fall through to
#: the fleet snapshot budget derivation)
ENV_BUDGET_MB = "CCX_DEVMEM_BUDGET_MB"


class Entry:
    """One device-resident object on the ledger."""

    __slots__ = ("klass", "key", "nbytes", "priority", "stamp", "pinned",
                 "job", "evictor")

    def __init__(self, klass: str, key: str, nbytes: int, priority: int,
                 stamp: int, pinned: bool, job: str | None, evictor) -> None:
        self.klass = klass
        self.key = key
        self.nbytes = int(nbytes)
        self.priority = int(priority)
        self.stamp = stamp
        self.pinned = pinned
        #: fleet job / session label — the scheduler's admission hook
        #: boosts a registering urgent job's entries by this label
        self.job = job
        #: callable(key) dropping the owner's device copy; owners hold
        #: only the device copy behind it, so calling it twice is safe
        self.evictor = evictor


class DeviceMemoryManager:
    """The ledger (module docstring). One process-wide instance
    (:data:`DEVMEM`) is shared by the snapshot registry, the placement
    store and the cost observatory's program accounting; tests and
    embedders may construct private instances with explicit budgets."""

    def __init__(self, budget_bytes: int | None = None,
                 metrics: bool = False) -> None:
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str], Entry] = {}
        self._seq = 0
        self._explicit_budget = budget_bytes
        #: (reason, priority-of-victim) -> count. Reasons: ``budget``
        #: (packing eviction), ``pressure`` (RESOURCE_EXHAUSTED flush),
        #: ``explicit`` (owner dropped/invalidated the entry itself)
        self.evictions: dict[tuple[str, int], int] = {}
        self.over_budget_admissions = 0
        self.admissions = 0
        #: export labeled gauges on the process registry (the singleton
        #: arms this; private test instances stay silent)
        self._metrics = metrics

    # ----- budget -----------------------------------------------------------

    def budget_bytes(self) -> int:
        """The unified HBM budget: explicit constructor/config/env
        override, else the costmodel derivation (capacity minus the
        captured program watermark, halved, floor 64 MB)."""
        if self._explicit_budget is not None and self._explicit_budget > 0:
            return int(self._explicit_budget)
        mb = _BUDGET_MB_CONFIG
        if mb is None:
            env = os.environ.get(ENV_BUDGET_MB)
            mb = float(env) if env else None
        if mb is not None and mb > 0:
            return int(mb * 1e6)
        from ccx.common import costmodel

        return costmodel.fleet_snapshot_budget_bytes()

    # ----- admission --------------------------------------------------------

    def admit(self, klass: str, key: str, nbytes: int, *,
              priority: int | None = None, job: str | None = None,
              pinned: bool = False, evictor=None) -> None:
        """Register (or refresh) a device-resident entry and pack the
        evictable classes under the budget. ``priority=None`` resolves
        to the ambient fleet job's priority, else an existing entry's
        priority (a metric graft refreshing a resident model must not
        demote it), else 0. Evictor callbacks run OUTSIDE the ledger
        lock — owners take their own locks inside them."""
        if priority is None:
            priority = self._ambient_priority()
        with self._lock:
            self._seq += 1
            cur = self._entries.get((klass, key))
            if priority is None:
                priority = cur.priority if cur is not None else 0
            e = Entry(klass, key, nbytes, priority, self._seq, pinned,
                      job if job is not None
                      else (cur.job if cur is not None else None),
                      evictor if evictor is not None
                      else (cur.evictor if cur is not None else None))
            self._entries[(klass, key)] = e
            self.admissions += 1
            victims = self._pick_victims(admit_priority=e.priority,
                                         protect=(klass, key))
        self._evict(victims, reason="budget")
        self._export()

    def touch(self, klass: str, key: str, *,
              priority: int | None = None,
              job: str | None = None) -> None:
        """LRU-refresh an entry (cache hit); ``priority`` — the toucher's
        job priority — becomes the entry's new priority (the last user
        wins, in both directions), and ``job`` relabels the entry with
        the toucher's fleet-job id (so a later ``touch_job`` from the
        scheduler's admission hook matches). No gauge export: a touch
        changes neither bytes nor eviction counts, and this is the
        per-cache-hit hot path."""
        with self._lock:
            e = self._entries.get((klass, key))
            if e is None:
                return
            self._seq += 1
            e.stamp = self._seq
            if priority is not None:
                e.priority = int(priority)
            if job is not None:
                e.job = job

    def touch_job(self, job: str, priority: int) -> None:
        """Boost/demote every entry carrying ``job`` as its fleet-job
        label to ``priority`` — the scheduler's admission hook: the
        moment an urgent job registers, its warm base and snapshot are
        protected from lower-priority packing for the job's duration
        (and a later normal-priority registration demotes them back).
        No gauge export — priorities are not gauged."""
        with self._lock:
            for e in self._entries.values():
                if e.job == job:
                    e.priority = int(priority)

    def release(self, klass: str, key: str, *,
                reason: str = "explicit") -> bool:
        """Remove an entry (the owner dropped/invalidated its device
        copy itself — LRU-install races, pressure flushes, puts). Does
        NOT call the evictor: the owner already did the dropping."""
        with self._lock:
            e = self._entries.pop((klass, key), None)
            if e is not None:
                k = (reason, e.priority)
                self.evictions[k] = self.evictions.get(k, 0) + 1
        self._export()
        return e is not None

    def release_namespace(self, ns: str, *, reason: str = "explicit") -> int:
        """Drop every entry whose key lives under ``ns + ":"`` — the
        teardown hook a registry/store arms via ``weakref.finalize`` so a
        dropped instance's entries never linger as phantom bytes on the
        shared ledger (tests and embedders construct and drop many)."""
        prefix = ns + ":"
        with self._lock:
            keys = [k for k in self._entries if k[1].startswith(prefix)]
            n = 0
            for k in keys:
                e = self._entries.pop(k)
                rk = (reason, e.priority)
                self.evictions[rk] = self.evictions.get(rk, 0) + 1
                n += 1
        self._export()
        return n

    # ----- eviction ---------------------------------------------------------

    def _pick_victims(self, admit_priority: int,
                      protect: tuple[str, str]) -> list[Entry]:
        """(lock held) Victims to bring the evictable classes under
        budget: lowest priority first, LRU within a priority; entries of
        STRICTLY higher priority than the admitter are untouchable (the
        urgent-vs-dryrun invariant), as are pinned entries and the
        just-admitted one. May come up short — the caller counts the
        over-budget admission and serves anyway."""
        budget = self.budget_bytes()
        total = sum(
            e.nbytes for e in self._entries.values()
            if e.klass in EVICTABLE_CLASSES
        )
        if total <= budget:
            return []
        candidates = sorted(
            (
                e for (kl, ky), e in self._entries.items()
                if kl in EVICTABLE_CLASSES and not e.pinned
                and (kl, ky) != protect and e.priority <= admit_priority
            ),
            key=lambda e: (e.priority, e.stamp),
        )
        victims: list[Entry] = []
        for e in candidates:
            if total <= budget:
                break
            del self._entries[(e.klass, e.key)]
            total -= e.nbytes
            k = ("budget", e.priority)
            self.evictions[k] = self.evictions.get(k, 0) + 1
            victims.append(e)
        if total > budget:
            self.over_budget_admissions += 1
        return victims

    def _evict(self, victims: list[Entry], reason: str) -> None:
        """Run the victims' owner callbacks outside the ledger lock (the
        owners take their own locks; a failing callback never wedges the
        ledger — the device copy it guards is already unaccounted)."""
        for e in victims:
            if e.evictor is None:
                continue
            try:
                e.evictor(e.key)
            except Exception:  # noqa: BLE001 — eviction is best-effort;
                pass  # the entry is gone from the ledger either way

    # ----- program residency ------------------------------------------------

    def note_program_watermark(self) -> None:
        """Refresh the pinned ``program`` entry from the cost
        observatory's captured HBM watermark — the compiled working set,
        priced exactly once (the auto budget derivation already
        subtracts the same number)."""
        try:
            from ccx.common import costmodel

            wm = int(costmodel.hbm_watermark_bytes())
        except Exception:  # noqa: BLE001 — accounting, never a dependency
            return
        if wm <= 0:
            return
        with self._lock:
            self._seq += 1
            self._entries[("program", "xla-working-set")] = Entry(
                "program", "xla-working-set", wm, 0, self._seq,
                pinned=True, job=None, evictor=None,
            )
        # no export here: the only caller is stats(), which exports once
        # at its end

    # ----- ambient priority -------------------------------------------------

    @staticmethod
    def _ambient_priority() -> int | None:
        """The calling thread's fleet-job priority (None = no ambient
        job — the caller's explicit/existing priority applies)."""
        try:
            from ccx.search.scheduler import FLEET

            h = FLEET.current()
            return None if h is None else int(h.priority)
        except Exception:  # noqa: BLE001 — scheduler import cycles in
            return None  # exotic embedders must not break admission

    # ----- observability ----------------------------------------------------

    def stats(self) -> dict:
        """The ledger block (``GET /observability``, ``AnalyzerState``,
        the steady-fleet bench's per-window samples): resident bytes and
        entry counts per class, eviction counts by reason and priority,
        the budget and whether the evictable classes respect it."""
        self.note_program_watermark()
        with self._lock:
            by_class_bytes: dict[str, int] = {}
            by_class_count: dict[str, int] = {}
            for e in self._entries.values():
                by_class_bytes[e.klass] = (
                    by_class_bytes.get(e.klass, 0) + e.nbytes
                )
                by_class_count[e.klass] = by_class_count.get(e.klass, 0) + 1
            evictable = sum(
                v for k, v in by_class_bytes.items()
                if k in EVICTABLE_CLASSES
            )
            evs = {
                f"{reason}/p{prio}": n
                for (reason, prio), n in sorted(self.evictions.items())
            }
            budget = self.budget_bytes()
            out = {
                "budgetBytes": budget,
                "residentBytes": by_class_bytes,
                "residentCount": by_class_count,
                "evictableBytes": evictable,
                "withinBudget": evictable <= budget,
                "evictions": evs,
                "evictionsTotal": sum(self.evictions.values()),
                "admissions": self.admissions,
                "overBudgetAdmissions": self.over_budget_admissions,
            }
        self._export()  # every stats read re-seeds the gauges (/metrics)
        return out

    def _export(self) -> None:
        """Push the labeled Prometheus gauges (singleton only): one
        ``devmem-resident-bytes`` series per class, one
        ``devmem-evictions`` series per (reason, priority), plus the
        scalar budget — all settable gauges, so the exposition stays one
        ``# TYPE`` per family (strict-parser-safe)."""
        if not self._metrics:
            return
        try:
            from ccx.common.metrics import REGISTRY

            with self._lock:
                by_class: dict[str, int] = {}
                for e in self._entries.values():
                    by_class[e.klass] = by_class.get(e.klass, 0) + e.nbytes
                evs = dict(self.evictions)
            for klass in ("snapshot", "warmBase", "program"):
                REGISTRY.set_gauge(
                    "devmem-resident-bytes", by_class.get(klass, 0),
                    labels={"class": klass},
                    help="device-resident bytes per ledger class "
                         "(ccx.common.devmem)",
                )
            REGISTRY.set_gauge(
                "devmem-budget-bytes", self.budget_bytes(),
                help="unified device-memory budget (ccx.common.devmem)",
            )
            for (reason, prio), n in evs.items():
                REGISTRY.set_gauge(
                    "devmem-evictions", n,
                    labels={"reason": reason, "priority": str(prio)},
                    help="ledger evictions by reason and victim priority "
                         "(ccx.common.devmem)",
                )
        except Exception:  # noqa: BLE001 — metrics are best-effort
            pass

    # ----- test/bench helpers -----------------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.evictions.clear()
            self.admissions = 0
            self.over_budget_admissions = 0
        self._export()

    def entry(self, klass: str, key: str) -> Entry | None:
        with self._lock:
            return self._entries.get((klass, key))


#: config-layer override (``optimizer.devmem.budget.mb`` via configure())
_BUDGET_MB_CONFIG: float | None = None


def configure(budget_mb: float | None = None) -> None:
    """Config hook (``optimizer.devmem.budget.mb``): 0/None restores the
    fleet-snapshot/auto derivation."""
    global _BUDGET_MB_CONFIG
    _BUDGET_MB_CONFIG = float(budget_mb) if budget_mb else None


#: the process-wide ledger (sidecar registry, placement store, facade and
#: bench all share it — like FLEET / TRACER / REGISTRY)
DEVMEM = DeviceMemoryManager(metrics=True)
