"""Host-side convergence analysis — plateau detection over per-chunk lex
cost series (ISSUE 9).

The device half lives in ``ccx.search.telemetry`` (the ring-buffer taps the
chunk engines carry); this module is the pure-python half every consumer
shares: the optimizer's plateau gauges, ``tools/convergence_report.py``
(the budget advisor), ``tools/bench_ledger.py`` (trend columns + the
past-plateau warning) and the flight-recorder ``summarize()`` join.

Deliberately dependency-light — stdlib only, no jax/numpy — so the ledger
and a dying TPU window's diagnosis tooling can import it instantly (the
same contract ``ccx.sidecar.wire`` keeps for remote clients).

Vocabulary (shared by every table and gauge built on top):

* a **series** is a list of per-chunk lex cost vectors (priority order —
  ``OptimizerResult.convergence`` segments carry one per engine run);
* the **plateau chunk** of a series is the index of the LAST chunk whose
  vector lexicographically improved on the best seen so far (beyond the
  engines' own significance tolerance, ``ccx.search.annealer.goal_tols``:
  ``atol + rtol * |best|``) — every later chunk was budget spent past
  convergence;
* the **wasted fraction** is (chunks after the plateau) / (chunks after
  the first) — the share of the run's chunk budget that no longer moved
  the lex vector. A chunk records the state at its END, so chunk 0 can
  never be "wasted": it bought the first measurement.
"""

from __future__ import annotations

#: significance tolerance for IMPROVEMENT, mirroring the engines'
#: ``goal_tols`` (a change smaller than this never flipped an acceptance
#: either)
RTOL = 1e-6
ATOL = 1e-6
#: coarser tolerance for REGRESSION: the descent engines accept
#: sub-tolerance upward drift in a high tier while a lower tier improves
#: (the batch-composition rule filters per move, and f32 accumulation
#: compounds over a 50-iteration chunk — measured at B5 lean:
#: PotentialNwOut +0.0003 on 250.21, +1.2e-6 relative, while NwOut fell
#: 98 → 65 in the same chunk). A symmetric tolerance would read that
#: chunk as "stopped improving" and the advisor would propose cutting a
#: budget that was buying real quality, so an upward change only blocks
#: improvement when it is significant at this coarser scale; anything
#: smaller reads as "equal" and the walk continues to lower tiers.
UP_RTOL = 1e-4
UP_ATOL = 1e-3

#: advisory past-plateau threshold shared by the budget advisor
#: (tools/convergence_report.py) and the ledger's warning
#: (tools/bench_ledger.py): a rung spending more than this share of its
#: chunks past plateau is flagged (WARN, never fail — shrinking a budget
#: is a retune decision for the advisor, not a gate)
WASTE_WARN = 0.30


def lex_improved(vec, best, rtol: float = RTOL, atol: float = ATOL,
                 up_rtol: float = UP_RTOL, up_atol: float = UP_ATOL) -> bool:
    """True when ``vec`` is lexicographically significantly below ``best``:
    walking tiers in priority order, the first decisively-changed goal
    moved down (asymmetric tolerances — see UP_RTOL above)."""
    for v, b in zip(vec, best):
        if v < b - (atol + rtol * abs(b)):
            return True
        if v > b + (up_atol + up_rtol * abs(b)):
            return False
    return False


def plateau_chunk(series) -> int:
    """Index of the last chunk whose lex vector improved on the running
    best (0 for an empty/single-chunk/never-improving series).

    Scalar series (plain energies, e.g. the flight recorder's tier-0
    heartbeat energies) are accepted too — each value is treated as a
    one-goal vector."""
    last = 0
    best = None
    for i, vec in enumerate(series):
        row = vec if isinstance(vec, (list, tuple)) else (vec,)
        if best is None:
            best = list(row)
            continue
        if lex_improved(row, best):
            best = list(row)
            last = i
    return last


def wasted_fraction(series) -> float:
    """Share of the series' chunks spent past the plateau (0.0..1.0)."""
    n = len(series)
    if n <= 1:
        return 0.0
    return (n - 1 - plateau_chunk(series)) / (n - 1)


def segment_stats(seg: dict) -> dict | None:
    """Plateau stats for ONE telemetry segment (the dict
    ``ccx.search.telemetry.decode`` emits: ``series`` + optional
    ``chunk``/``budget``/``truncated``). None when the segment carries no
    usable series."""
    series = seg.get("series") or []
    if not series:
        return None
    plateau = plateau_chunk(series)
    n = len(series)
    out = {
        "chunks": n,
        "plateauChunk": plateau,
        "wastedFraction": round(wasted_fraction(series), 4),
        "truncated": bool(seg.get("truncated")),
    }
    chunk = seg.get("chunk")
    budget = seg.get("budget")
    if chunk:
        out["chunkSize"] = int(chunk)
        # budget units (SA steps / descent iterations) covered through the
        # plateau chunk's END — the floor any retune must keep
        out["plateauBudget"] = int((plateau + 1) * chunk)
    if budget is not None:
        out["budget"] = int(budget)
    return out


def propose_budget(seg: dict, margin: float = 1.25) -> int | None:
    """Retuned per-phase budget proposal: the budget units spent through
    the plateau chunk, plus a safety margin, capped at the configured
    budget (never propose MORE than was configured) and floored at one
    chunk. None when the segment lacks chunk sizing.

    A truncated segment (more chunks ran than the ring buffer holds) only
    proves the plateau is AT OR AFTER the last retained early row — the
    proposal is then the configured budget itself (no evidence to shrink
    on)."""
    st = segment_stats(seg)
    if st is None or "chunkSize" not in st:
        return None
    budget = st.get("budget")
    if st["truncated"]:
        return budget
    proposed = int(st["plateauBudget"] * margin)
    chunk = st["chunkSize"]
    proposed = max(proposed, chunk)
    if budget is not None:
        proposed = min(proposed, budget)
    return proposed


def phase_table(convergence: dict) -> list[dict]:
    """Flatten an ``OptimizerResult.convergence`` block into per-phase
    advisor rows (last segment per phase — the converged run; earlier
    segments of a multi-run phase, e.g. repair-round re-polishes, are
    summed into the wasted totals but not re-proposed)."""
    rows: list[dict] = []
    for phase, segs in (convergence.get("phases") or {}).items():
        segs = [s for s in segs if s.get("series")]
        if not segs:
            continue
        last = segs[-1]
        st = segment_stats(last) or {}
        total_chunks = sum(len(s["series"]) for s in segs)
        # truncated segments carry a GAPPY ring (opening rows + the
        # latest chunk): the retained rows say nothing about where the
        # missing middle plateaued, so — like propose_budget — they
        # contribute no waste evidence
        full = [s for s in segs if not s.get("truncated")]
        past = sum(
            max(len(s["series"]) - 1 - plateau_chunk(s["series"]), 0)
            for s in full
        )
        steppable = sum(max(len(s["series"]) - 1, 0) for s in full)
        rows.append({
            "phase": phase,
            "segments": len(segs),
            "chunks": total_chunks,
            "plateauChunk": st.get("plateauChunk"),
            "wastedFraction": (
                round(past / steppable, 4) if steppable else 0.0
            ),
            "chunkSize": st.get("chunkSize"),
            "budget": st.get("budget"),
            "proposedBudget": propose_budget(last),
            "truncated": st.get("truncated", False),
        })
    rows.sort(key=lambda r: -(r["wastedFraction"] or 0.0))
    return rows


def ladder_summary(seg: dict) -> dict | None:
    """Replica-exchange ladder roll-up for ONE telemetry segment (ISSUE
    16): total/accepted exchange pairs, the overall acceptance rate, and
    the ladder geometry the annealer attached (``nTemps``, ``interval``,
    ``rungSize``, ``endTemps``). None for flat segments — no ``exchange``
    series or nothing attempted — so every consumer can print it
    conditionally without schema checks.

    Exchange-acceptance rate is the classic ladder-health gauge: near 0
    the rungs are too far apart to communicate (the ladder degenerates to
    independent restarts), near 1 they are so close the exchange buys no
    mixing; the 20-40% band is the usual target. The report prints it per
    phase so a campaign retune can tune ``n_temps`` from evidence."""
    ex = seg.get("exchange") or {}
    attempted = ex.get("attempted") or []
    total_att = sum(int(a) for a in attempted)
    if total_att <= 0:
        return None
    accepted = ex.get("accepted") or []
    total_acc = sum(int(a) for a in accepted)
    out = {
        "attempted": total_att,
        "accepted": total_acc,
        "acceptRate": round(total_acc / total_att, 4),
        "sweeps": sum(1 for a in attempted if int(a) > 0),
    }
    ladder = seg.get("ladder") or {}
    for k in ("nTemps", "interval", "rungSize", "t0", "endTemps"):
        if k in ladder:
            out[k] = ladder[k]
    return out


def total_wasted_fraction(convergence: dict) -> float:
    """Whole-run share of chunk budget past plateau, across every phase
    and segment — the single number the ledger's >WASTE_WARN warning
    gates. Truncated segments are skipped (the ring kept only the opening
    rows + the latest chunk — no evidence of where the middle plateaued),
    matching ``propose_budget``'s never-shrink-on-truncation rule."""
    past = steppable = 0
    for segs in (convergence.get("phases") or {}).values():
        for s in segs:
            series = s.get("series") or []
            if len(series) <= 1 or s.get("truncated"):
                continue
            steppable += len(series) - 1
            past += len(series) - 1 - plateau_chunk(series)
    return past / steppable if steppable else 0.0
