"""Metrics registry — timers/meters/gauges/histograms with a Prometheus
text-exposition view.

Parity: the reference exports Dropwizard ``MetricRegistry`` timers and
meters over JMX domain ``kafka.cruisecontrol`` — e.g. GoalOptimizer's
``proposal-computation-timer`` and per-endpoint servlet timers (SURVEY.md
§5.1/§5.5). Python has no JMX; the idiomatic equivalent is a registry
rendered in Prometheus text exposition format (version 0.0.4), which
SURVEY.md §7.2 step 5 prescribes for the rebuild.

Exposition contract (pinned by tests/test_observability.py with a strict
format parser): every metric family gets ``# HELP`` and ``# TYPE`` lines;
timers render as summaries (``_seconds_sum``/``_seconds_count``) plus a
``_seconds_max`` gauge; counters follow the ``_total`` naming convention;
histograms emit cumulative ``_bucket{le=...}`` series ending at ``+Inf``.
The servlet serves it with ``PROMETHEUS_CONTENT_TYPE``.
"""

from __future__ import annotations

import math
import threading
import time

#: the text-exposition content type the /metrics endpoint must serve
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class Timer:
    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def time(self):
        registry_timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                registry_timer.update(time.monotonic() - self.t0)
                return False

        return _Ctx()

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Counter:
    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """A SETTABLE gauge (vs the callable-backed gauges ``gauge()``
    registers): holds the last value written. Used for push-style live
    state — e.g. the convergence taps' per-job energy/plateau gauges,
    where the producer (a chunk heartbeat) knows the value and no
    callable could recompute it."""

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Prometheus-style cumulative histogram. The default buckets span
    5 ms .. 10 min — sized for optimizer phases and sidecar RPCs, where
    the interesting tail is a multi-minute TPU compile, not a microsecond
    cache hit."""

    DEFAULT_BUCKETS = (
        0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
        30.0, 60.0, 120.0, 300.0, 600.0,
    )

    def __init__(self, buckets: tuple[float, ...] | None = None) -> None:
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)  # last slot = +Inf
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        """Cumulative per-bucket counts keyed by upper bound (+Inf last)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self.count, self.sum
        cum = 0
        out: dict = {"count": total, "sum": s, "buckets": {}}
        for le, c in zip(self.buckets, counts):
            cum += c
            out["buckets"][le] = cum
        out["buckets"][math.inf] = total
        return out


def _fmt_le(le: float) -> str:
    if math.isinf(le):
        return "+Inf"
    s = f"{le:g}"
    return s


class MetricsRegistry:
    """Process-wide named timers/counters/gauges/histograms (ref
    MetricRegistry)."""

    def __init__(self, prefix: str = "ccx") -> None:
        self.prefix = prefix
        self._timers: dict[str, Timer] = {}
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, object] = {}  # name -> callable() -> float
        #: settable gauges, composite-keyed like histograms when labeled
        #: ('name|[["k","v"],...]'); one family may NOT also be a
        #: callable gauge (duplicate TYPE) — naming keeps them apart
        self._gauge_values: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _label_key(name: str, labels: dict | None) -> str:
        import json as _json

        if not labels:
            return name
        return name + "|" + _json.dumps(
            sorted((str(k), str(v)) for k, v in labels.items())
        )

    def _set_help(self, name: str, help: str | None) -> None:
        if help and name not in self._help:
            self._help[name] = help

    def timer(self, name: str, help: str | None = None) -> Timer:
        with self._lock:
            self._set_help(name, help)
            return self._timers.setdefault(name, Timer())

    def counter(self, name: str, help: str | None = None) -> Counter:
        with self._lock:
            self._set_help(name, help)
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str, fn, help: str | None = None) -> None:
        with self._lock:
            self._set_help(name, help)
            self._gauges[name] = fn

    def histogram(self, name: str, buckets: tuple[float, ...] | None = None,
                  help: str | None = None,
                  labels: dict[str, str] | None = None) -> Histogram:
        """``labels`` (e.g. ``{"job": cluster_id}`` — fleet serving's
        per-job span histograms) keys a separate series of the SAME metric
        family: one ``# TYPE`` declaration, one ``_bucket``/``_sum``/
        ``_count`` series per label set, labels merged with ``le`` on the
        bucket lines. Label VALUES are arbitrary strings (cluster ids come
        off the wire) — the composite key holds them JSON-encoded so
        ``,``/``=``/``"`` can neither corrupt the key nor the exposition."""
        key = self._label_key(name, labels)
        with self._lock:
            self._set_help(name, help)
            return self._histograms.setdefault(key, Histogram(buckets))

    def set_gauge(self, name: str, value: float,
                  labels: dict[str, str] | None = None,
                  help: str | None = None) -> Gauge:
        """Write a settable gauge series (same label contract as
        ``histogram``): one ``# TYPE gauge`` family, one sample line per
        label set. Used by the convergence taps for the live per-job
        energy / per-phase plateau-step gauges (ISSUE 9)."""
        key = self._label_key(name, labels)
        with self._lock:
            self._set_help(name, help)
            g = self._gauge_values.setdefault(key, Gauge())
        g.set(value)
        return g

    def render_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of everything registered:
        ``# HELP`` + ``# TYPE`` per family, summaries for timers,
        ``_total`` counters, gauges, cumulative histograms."""
        out: list[str] = []

        def sanitize(name: str) -> str:
            return name.replace("-", "_").replace(".", "_").replace(" ", "_")

        def esc(text: str) -> str:
            return text.replace("\\", "\\\\").replace("\n", "\\n")

        with self._lock:
            timers = dict(self._timers)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            gauge_values = dict(self._gauge_values)
            histograms = dict(self._histograms)
            helps = dict(self._help)

        def head(raw_name: str, family: str, typ: str, default_help: str):
            out.append(
                f"# HELP {family} {esc(helps.get(raw_name, default_help))}"
            )
            out.append(f"# TYPE {family} {typ}")

        for name, t in sorted(timers.items()):
            n = f"{self.prefix}_{sanitize(name)}_seconds"
            head(name, n, "summary", f"{name} timer (seconds)")
            out.append(f"{n}_sum {t.total_s:.6f}")
            out.append(f"{n}_count {t.count}")
            head(name + "/max", f"{n}_max", "gauge",
                 f"{name} timer max single observation (seconds)")
            out.append(f"{n}_max {t.max_s:.6f}")
        for name, c in sorted(counters.items()):
            n = f"{self.prefix}_{sanitize(name)}_total"
            head(name, n, "counter", f"{name} counter")
            out.append(f"{n} {c.value}")
        for name, fn in sorted(gauges.items()):
            try:
                v = float(fn())
            except Exception:
                continue
            n = f"{self.prefix}_{sanitize(name)}"
            head(name, n, "gauge", f"{name} gauge")
            out.append(f"{n} {v}")
        # labeled series ('name|[["k","v"],...]' — JSON-packed label
        # pairs) share one family — HELP/TYPE emitted once per family
        # (the strict exposition parser forbids duplicate TYPE
        # declarations). Label values escape \ " and newline per the
        # exposition format.
        import json as _json

        def esc_label(v: str) -> str:
            return (
                v.replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def label_str(labelstr: str) -> str:
            if not labelstr:
                return ""
            inner = ",".join(
                f'{sanitize(k)}="{esc_label(v)}"'
                for k, v in _json.loads(labelstr)
            )
            return "{" + inner + "}"

        # settable gauges (push-style — convergence energy/plateau): one
        # gauge family per name, one sample per label set, grouped so
        # every sample follows its family's TYPE line
        declared_g: set[str] = set()
        for key, g in sorted(
            gauge_values.items(),
            key=lambda kv: (kv[0].partition("|")[0], kv[0]),
        ):
            name, _, labelstr = key.partition("|")
            n = f"{self.prefix}_{sanitize(name)}"
            if n not in declared_g:
                declared_g.add(n)
                head(name, n, "gauge", f"{name} gauge")
            out.append(f"{n}{label_str(labelstr)} {g.value}")

        declared: set[str] = set()
        for key, h in sorted(
            histograms.items(), key=lambda kv: (kv[0].partition("|")[0], kv[0])
        ):
            name, _, labelstr = key.partition("|")
            n = f"{self.prefix}_{sanitize(name)}"
            snap = h.snapshot()
            if n not in declared:
                declared.add(n)
                head(name, n, "histogram", f"{name} histogram")
            extra = ""
            if labelstr:
                series = label_str(labelstr)
                extra = "," + series[1:-1]
            else:
                series = ""
            for le, cum in snap["buckets"].items():
                out.append(f'{n}_bucket{{le="{_fmt_le(le)}"{extra}}} {cum}')
            out.append(f"{n}_sum{series} {snap['sum']:.6f}")
            out.append(f"{n}_count{series} {snap['count']}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "timers": {
                    k: {"count": t.count, "meanSec": t.mean_s, "maxSec": t.max_s}
                    for k, t in self._timers.items()
                },
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {
                    k: g.value for k, g in self._gauge_values.items()
                },
                "histograms": {
                    k: {"count": h.count, "sumSec": round(h.sum, 4)}
                    for k, h in self._histograms.items()
                },
            }


#: the process-wide default registry (ref: the app's single MetricRegistry)
REGISTRY = MetricsRegistry()
