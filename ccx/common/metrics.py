"""Metrics registry — timers/meters/gauges with a Prometheus-text view.

Parity: the reference exports Dropwizard ``MetricRegistry`` timers and
meters over JMX domain ``kafka.cruisecontrol`` — e.g. GoalOptimizer's
``proposal-computation-timer`` and per-endpoint servlet timers (SURVEY.md
§5.1/§5.5). Python has no JMX; the idiomatic equivalent is a registry
rendered in Prometheus text exposition format, which SURVEY.md §7.2 step 5
prescribes for the rebuild.
"""

from __future__ import annotations

import threading
import time


class Timer:
    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def time(self):
        registry_timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                registry_timer.update(time.monotonic() - self.t0)
                return False

        return _Ctx()

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Counter:
    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class MetricsRegistry:
    """Process-wide named timers/counters/gauges (ref MetricRegistry)."""

    def __init__(self, prefix: str = "ccx") -> None:
        self.prefix = prefix
        self._timers: dict[str, Timer] = {}
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, object] = {}  # name -> callable() -> float
        self._lock = threading.Lock()

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str, fn) -> None:
        with self._lock:
            self._gauges[name] = fn

    def render_prometheus(self) -> str:
        """Prometheus text exposition of everything registered."""
        out: list[str] = []

        def sanitize(name: str) -> str:
            return name.replace("-", "_").replace(".", "_").replace(" ", "_")

        with self._lock:
            timers = dict(self._timers)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        for name, t in sorted(timers.items()):
            n = f"{self.prefix}_{sanitize(name)}"
            out.append(f"# TYPE {n}_seconds_total counter")
            out.append(f"{n}_seconds_total {t.total_s:.6f}")
            out.append(f"{n}_count {t.count}")
            out.append(f"{n}_seconds_max {t.max_s:.6f}")
        for name, c in sorted(counters.items()):
            n = f"{self.prefix}_{sanitize(name)}"
            out.append(f"# TYPE {n} counter")
            out.append(f"{n} {c.value}")
        for name, fn in sorted(gauges.items()):
            n = f"{self.prefix}_{sanitize(name)}"
            try:
                v = float(fn())
            except Exception:
                continue
            out.append(f"# TYPE {n} gauge")
            out.append(f"{n} {v}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "timers": {
                    k: {"count": t.count, "meanSec": t.mean_s, "maxSec": t.max_s}
                    for k, t in self._timers.items()
                },
                "counters": {k: c.value for k, c in self._counters.items()},
            }


#: the process-wide default registry (ref: the app's single MetricRegistry)
REGISTRY = MetricsRegistry()
