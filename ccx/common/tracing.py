"""Flight-recorder tracing — structured spans, chunk heartbeats, stall
watchdog. The observability layer the TPU campaign runs under so a burned
window is never blind (ISSUE 5; ROADMAP "Bank the number on hardware").

Five rounds of TPU windows died without evidence: a >17-min compile that
timed out, a SIGKILLed process whose ``phase_seconds`` dict evaporated with
it. The reference's host-side story is Dropwizard timers + OperationProgress
(SURVEY.md §5.1/§5.5); production reconfiguration systems treat live
per-stage telemetry as the prerequisite for diagnosing stalls mid-flight
(PAPERS.md "Integrative Dynamic Reconfiguration..."). This module is that
layer for the TPU-native pipeline, in three pieces:

**Spans.** ``TRACER.span(name, kind=..., **attrs)`` wraps a code region:
wall time, caller-supplied shape/config attributes, and the compile
attribution that fired inside it (``ccx.common.compilestats`` deltas — the
"which phase paid that 17-minute compile" answer). Spans nest per thread;
a completed root span's tree is exported three ways: ``OptimizerResult.
span_tree`` (→ BENCH lines and the sidecar result), ``AnalyzerState.
observability`` over REST, and per-phase/per-RPC Prometheus histograms in
``ccx.common.metrics``. Timing is host wall-clock by default; with
``observability.trace.sync`` (config) / ``CCX_TRACE_SYNC=1`` (env) every
span close drains the device stream first (``block_until_ready`` on a
freshly dispatched scalar — in-order execution makes that an upper bound on
prior queued work), trading dispatch-pipeline overlap for device-honest
per-phase walls. Default OFF: the pipelined repair/anneal overlap is a
measured win the default must not silently forfeit.

**Flight recorder.** ``arm(path)`` (config ``observability.flight.recorder.
path`` or env ``CCX_FLIGHT_RECORDER``) streams every span start/end, every
chunk heartbeat (one record per ``drive_chunks`` sync point — phase, chunk
index, compile counters), and watchdog dumps to a JSONL file. Crash-safe
by construction: each record is ONE ``os.write`` to an ``O_APPEND`` fd —
atomic for regular files, and OS-buffered data survives SIGKILL — so a
killed or driver-timed-out run leaves a file whose last line names the
exact phase, chunk index, and cumulative compile attribution at death.
Parse it with ``python -m ccx.common.tracing <file>`` or see
docs/observability.md ("how to read a dead window's recording").

**Stall watchdog.** With ``observability.watchdog.seconds`` > 0 (env
``CCX_WATCHDOG_SECONDS``) a daemon thread watches the event stream; when
no span event or heartbeat arrives for that long while spans are active,
it dumps all-thread stacks, the active span stacks, and live compilestats
into the recorder (and stderr) — one dump per stall episode, re-armed by
the next heartbeat. A wedged device or a pathological compile therefore
self-reports from inside the dying process.

Overhead contract (pinned by tests/test_observability.py): spans and
heartbeats are host-side only — no jax arrays are touched unless
``sync`` is explicitly enabled — so tracing can never perturb program
shapes or cost a warm rung a recompile; unarmed, a heartbeat is two
attribute writes and a timestamp.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
import traceback

#: recorder schema version, stamped on every ``arm`` header record
RECORDER_VERSION = 1

#: env knobs (the config keys ``observability.*`` take precedence when a
#: facade is constructed; env covers bench/tools/subprocess paths)
ENV_RECORDER = "CCX_FLIGHT_RECORDER"
ENV_WATCHDOG = "CCX_WATCHDOG_SECONDS"
ENV_SYNC = "CCX_TRACE_SYNC"


def _device_sync() -> None:
    """Drain the device stream (best effort): dispatch a trivial scalar and
    block on it — per-device execution is in-order, so this bounds every
    previously queued program. Never raises (a wedged device must not turn
    a span close into a hang worse than the one being measured — the call
    itself may block, which IS the honest timing)."""
    try:
        import jax
        import jax.numpy as jnp

        jax.block_until_ready(jnp.zeros(()) + 0)
    except Exception:  # noqa: BLE001 — tracing must never break the host
        pass


class Span:
    """One traced region. Mutable fields are written by the owning thread
    only; the watchdog reads paths/attrs without a lock (stale reads are
    acceptable in a stall dump)."""

    __slots__ = (
        "name", "kind", "path", "attrs", "children", "t_wall",
        "t0", "wall_s", "compile0", "compile", "cost0", "cost_delta", "done",
    )

    def __init__(self, name: str, kind: str | None, path: str,
                 attrs: dict, compile0: dict | None) -> None:
        self.name = name
        self.kind = kind
        self.path = path
        self.attrs = attrs
        self.children: list[Span] = []
        self.t_wall = time.time()
        self.t0 = time.monotonic()
        self.wall_s: float | None = None
        self.compile0 = compile0
        self.compile: dict | None = None
        self.cost0 = _cost_snapshot()
        self.cost_delta: dict | None = None
        self.done = False

    def to_json(self) -> dict:
        out: dict = {"name": self.name, "startedAt": round(self.t_wall, 3)}
        if self.kind:
            out["kind"] = self.kind
        if self.wall_s is not None:
            out["wallSeconds"] = round(self.wall_s, 4)
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.compile:
            out["compile"] = self.compile
        cost = _cost_compact(self.cost_delta)
        if cost:
            # expected device time + HBM watermark of the programs this
            # span executed (ccx.common.costmodel roofline) — the
            # quantitative half of the flight-recorder readout
            out["costModel"] = cost
        if self.children:
            out["children"] = [c.to_json() for c in self.children]
        return out


def _compile_snapshot() -> dict | None:
    """Live compilestats counters; None when jax is unimportable (keeps the
    tracer usable from dependency-light tools)."""
    try:
        from ccx.common import compilestats

        return compilestats.snapshot()
    except Exception:  # noqa: BLE001
        return None


def _cost_snapshot() -> dict | None:
    """Live cost-observatory execution counters (ccx.common.costmodel) —
    None-tolerant for dependency-light tools, same as compile counters."""
    try:
        from ccx.common import costmodel

        return costmodel.exec_snapshot()
    except Exception:  # noqa: BLE001
        return None


def _cost_exec_delta(before: dict | None) -> dict | None:
    if before is None:
        return None
    try:
        from ccx.common import costmodel

        return costmodel.exec_delta(before) or None
    except Exception:  # noqa: BLE001
        return None


def _cost_compact(delta: dict | None) -> dict | None:
    """Span-sized cost rollup: the phase's expected device seconds + HBM
    watermark. Computed lazily (at to_json/record time) so a cold run's
    spans pick up records the end-of-run capture flush banked AFTER the
    span closed."""
    if not delta:
        return None
    try:
        from ccx.common import costmodel

        return costmodel.projection_compact(delta)
    except Exception:  # noqa: BLE001
        return None


def _compile_delta(before: dict | None) -> dict | None:
    if before is None:
        return None
    after = _compile_snapshot()
    if after is None:
        return None
    from ccx.common import compilestats

    d = compilestats.delta(before, after)
    return d if any(d.values()) else None


class Tracer:
    def __init__(self) -> None:
        self._tl = threading.local()
        self._lock = threading.Lock()
        #: thread ident -> that thread's live span stack (for the watchdog
        #: and the REST observability view)
        self._stacks: dict[int, list[Span]] = {}
        self._fd: int | None = None
        self._path: str | None = None
        self._records = 0
        self.sync = False
        self._last_event = time.monotonic()
        #: per-thread last event time (GIL-atomic dict writes): stall
        #: detection must be per thread, or a healthy Ping span every 60 s
        #: would mask a Propose worker wedged in a 17-minute compile
        self._thread_last: dict[int, float] = {}
        #: threads already dumped for the CURRENT stall episode
        self._stalled_dumped: set[int] = set()
        self._watchdog_s = 0.0
        self._watchdog_stop: threading.Event | None = None
        self._watchdog_thread: threading.Thread | None = None
        self._watchdog_dumps = 0
        self._last_root: dict | None = None
        self._env_checked = False
        #: live record taps (sidecar Propose streams heartbeats to the JVM
        #: through one) — called with each record dict, never raising
        self._listeners: list = []
        #: per-job convergence timeline (ISSUE 9): the last N heartbeat
        #: energies per job label ("" = no fleet job), LRU-bounded so a
        #: long fleet run cannot grow it without bound. Feeds the
        #: /observability per-job section and the VIEWER-safe summary.
        self._energy: collections.OrderedDict = collections.OrderedDict()

    # ----- configuration ----------------------------------------------------

    def _maybe_env(self) -> None:
        """One-shot env arming: lets ANY proposal path (bench subprocess,
        campaign rung, kill-test child) leave a recording without code —
        export CCX_FLIGHT_RECORDER and the first span arms it."""
        if self._env_checked:
            return
        self._env_checked = True
        if os.environ.get(ENV_SYNC) == "1":
            self.sync = True
        wd = os.environ.get(ENV_WATCHDOG)
        if wd:
            try:
                self.set_watchdog(float(wd))
            except ValueError:
                pass
        path = os.environ.get(ENV_RECORDER)
        if path and self._fd is None:
            try:
                self.arm(path)
            except OSError:
                pass

    def configure(self, sync: bool | None = None,
                  watchdog_seconds: float | None = None,
                  path: str | None = None) -> None:
        """Config-driven setup (facade construction). ``path``/knobs left
        None keep their current (possibly env-armed) values."""
        self._maybe_env()
        if sync is not None:
            self.sync = bool(sync)
        if watchdog_seconds is not None:
            self.set_watchdog(float(watchdog_seconds))
        if path:
            self.arm(path)

    def arm(self, path: str) -> None:
        """Open (append) the flight-recorder file and write the header
        record. Re-arming on the same path is a no-op; a new path closes
        the old recorder first."""
        with self._lock:
            if self._fd is not None and self._path == path:
                return
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
            self._fd = os.open(
                path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
            self._path = path
            self._records = 0
        self._record({
            "ev": "arm", "v": RECORDER_VERSION, "pid": os.getpid(),
            "argv": sys.argv[:4],
        })

    def disarm(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
            self._fd = None
            self._path = None

    def set_watchdog(self, seconds: float) -> None:
        """(Re)arm the stall watchdog; 0 stops it."""
        self._watchdog_s = max(float(seconds), 0.0)
        if self._watchdog_s <= 0:
            if self._watchdog_stop is not None:
                self._watchdog_stop.set()
                self._watchdog_thread = None
                self._watchdog_stop = None
            return
        if self._watchdog_thread is None or not self._watchdog_thread.is_alive():
            self._watchdog_stop = threading.Event()
            self._watchdog_thread = threading.Thread(
                target=self._watch, name="ccx-stall-watchdog", daemon=True
            )
            self._watchdog_thread.start()

    # ----- per-job labels (fleet serving) -----------------------------------

    def set_job(self, job_id: str | None) -> str | None:
        """Set this thread's job label (the fleet scheduler's cluster id —
        ccx.search.scheduler): every span record, chunk heartbeat and span
        histogram the thread emits while set carries ``job=<cluster-id>``,
        so an interleaved multi-job trace is attributable per job instead
        of landing on one anonymous phase span. Returns the previous label
        (restore it when the job ends)."""
        prev = getattr(self._tl, "job", None)
        self._tl.job = job_id
        return prev

    def job(self) -> str | None:
        return getattr(self._tl, "job", None)

    # ----- spans ------------------------------------------------------------

    def _stack(self) -> list[Span]:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
            with self._lock:
                self._stacks[threading.get_ident()] = st
        return st

    def start(self, name: str, kind: str | None = None, **attrs) -> Span:
        self._maybe_env()
        st = self._stack()
        path = (st[-1].path + "/" + name) if st else name
        job = self.job()
        if job is not None and "job" not in attrs:
            # per-job attribution (fleet serving): the span tree and every
            # recorder line under it name which cluster's job this is
            attrs = {"job": job, **attrs}
        s = Span(name, kind, path, attrs, _compile_snapshot())
        if st:
            st[-1].children.append(s)
        st.append(s)
        self._record({
            "ev": "start", "span": path,
            **({"kind": kind} if kind else {}),
            **({"attrs": attrs} if attrs else {}),
        })
        return s

    def end(self, span: Span) -> None:
        if span.done:
            return
        if self.sync:
            _device_sync()
        span.wall_s = time.monotonic() - span.t0
        span.compile = _compile_delta(span.compile0)
        span.cost_delta = _cost_exec_delta(span.cost0)
        span.done = True
        st = getattr(self._tl, "stack", None)
        root_closed = False
        if st is not None and span in st:
            # pop through to this span — an unwound exception may leave
            # unclosed children above it; close them with honest walls
            while st and st[-1] is not span:
                inner = st.pop()
                if not inner.done:
                    inner.wall_s = time.monotonic() - inner.t0
                    inner.done = True
            if st and st[-1] is span:
                st.pop()
            root_closed = not st
        cost = _cost_compact(span.cost_delta)
        self._record({
            "ev": "end", "span": span.path,
            "wall_s": round(span.wall_s, 4),
            **({"compile": span.compile} if span.compile else {}),
            # expected device seconds + HBM watermark for the programs the
            # span ran: a later wedge in the SAME phase reads its expected
            # cost off this record (summarize() joins them)
            **({"cost": cost} if cost else {}),
        })
        if root_closed:
            # root closed: bank the tree and deregister this thread's
            # stack — the sidecar spawns a worker thread per Propose, so
            # keeping dead-thread entries would grow the registry (and
            # every watchdog/REST scan of it) without bound. The next
            # span on this thread re-registers via _stack(). Must run
            # AFTER the end record above — _record re-stamps this
            # thread's liveness entry, which would undo the pop.
            tid = threading.get_ident()
            self._tl.stack = None
            with self._lock:
                self._last_root = span.to_json()
                self._stacks.pop(tid, None)
            self._thread_last.pop(tid, None)
        if span.kind:
            # bucketed per-phase / per-RPC / per-verb latency — the
            # Prometheus face of the span stream. Spans closed under a
            # fleet job get a ``job=<cluster-id>`` label series so an
            # interleaved trace's histograms attribute per cluster.
            from ccx.common.metrics import REGISTRY

            job = self.job()
            REGISTRY.histogram(
                f"{span.kind}-{span.name}-seconds",
                help=f"ccx {span.kind} '{span.name}' wall seconds (span close)",
                labels={"job": job} if job is not None else None,
            ).observe(span.wall_s)

    @contextlib.contextmanager
    def span(self, name: str, kind: str | None = None, **attrs):
        s = self.start(name, kind=kind, **attrs)
        try:
            yield s
        finally:
            self.end(s)

    def heartbeat(self, chunk: int, offset: int | None = None,
                  total: int | None = None,
                  energy: float | None = None) -> None:
        """One record per host↔device chunk sync point (``annealer.
        drive_chunks``). Unarmed cost: two attr writes + a timestamp.

        ``energy`` (ISSUE 9 — the convergence taps' tier-0 lex cost,
        possibly one chunk stale on sync-free SA drives) joins the span
        attrs, the recorder line, the per-job convergence timeline and
        the live ``convergence-energy`` Prometheus gauge — a wedged
        window's last JSONL line then names phase + chunk + QUALITY, not
        just depth."""
        st = getattr(self._tl, "stack", None)
        span = st[-1] if st else None
        if span is not None:
            span.attrs["chunk"] = int(chunk)
            if total is not None:
                span.attrs["chunkTotal"] = int(total)
            if energy is not None:
                span.attrs["energy"] = round(float(energy), 4)
        if energy is not None:
            self._note_energy(
                energy, chunk, span.path if span is not None else None
            )
        if self._fd is None and not self._listeners:
            now = time.monotonic()
            tid = threading.get_ident()
            self._last_event = now
            self._thread_last[tid] = now
            self._stalled_dumped.discard(tid)
            return
        rec = {"ev": "chunk", "chunk": int(chunk)}
        if span is not None:
            rec["span"] = span.path
        if offset is not None:
            rec["offset"] = int(offset)
        if total is not None:
            rec["total"] = int(total)
        if energy is not None:
            rec["energy"] = round(float(energy), 4)
        snap = _compile_snapshot()
        if snap is not None:
            rec["compile"] = snap
        self._record(rec)

    def healing_event(self, phase: str, **attrs) -> None:
        """One structured healing-timeline record (ISSUE 20 — the
        closed-loop control plane): ``phase`` is detected / fired /
        recovered / forecast / fire-failed, attrs carry cluster, family,
        cause, verb and the episode id. Rides the same O_APPEND JSONL
        stream as spans and heartbeats, so a dead soak run's flight
        recording still names the episode in progress — and
        ``summarize()`` joins the phases back into per-episode arcs."""
        if self._fd is None and not self._listeners:
            return
        self._record({"ev": "healing", "phase": str(phase), **attrs})

    # ----- convergence timeline (ISSUE 9) -----------------------------------

    #: heartbeat energies retained per job / jobs retained (LRU)
    ENERGY_WINDOW = 64
    ENERGY_JOBS = 32

    def _note_energy(self, energy: float, chunk: int,
                     span: str | None) -> None:
        job = self.job() or ""
        entry = {"chunk": int(chunk), "energy": round(float(energy), 4)}
        if span is not None:
            entry["span"] = span
        with self._lock:
            dq = self._energy.get(job)
            if dq is None:
                dq = self._energy[job] = collections.deque(
                    maxlen=self.ENERGY_WINDOW
                )
            dq.append(entry)
            self._energy.move_to_end(job)
            while len(self._energy) > self.ENERGY_JOBS:
                self._energy.popitem(last=False)
        try:
            from ccx.common.metrics import REGISTRY

            REGISTRY.set_gauge(
                "convergence-energy", float(energy),
                labels={"job": job} if job else None,
                help="tier-0 lex energy at the last chunk heartbeat "
                     "(convergence taps, per fleet job)",
            )
        except Exception:  # noqa: BLE001 — enrichment must never raise
            pass

    def convergence_timeline(self) -> dict:
        """Per-job heartbeat-energy series (last ENERGY_WINDOW chunks per
        job) — the /observability convergence section."""
        with self._lock:
            return {job: list(dq) for job, dq in self._energy.items()}

    def convergence_summary(self) -> dict:
        """VIEWER-safe compact form: last energy + chunk per job, no
        series, no span stacks."""
        with self._lock:
            return {
                job: {**dq[-1], "beats": len(dq)}
                for job, dq in self._energy.items()
                if dq
            }

    # ----- recorder ---------------------------------------------------------

    def add_listener(self, fn) -> None:
        """Tap the record stream (every span start/end, heartbeat, watchdog
        dump — armed or not). Used by the sidecar to relay heartbeats as
        Propose progress frames. ``fn(rec)`` must be fast and non-raising;
        exceptions are swallowed."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _record(self, rec: dict, event: bool = True) -> None:
        # watchdog dumps pass event=False: the dump's own write must not
        # count as liveness, or one stall would re-arm the watchdog into
        # dumping every interval instead of once per episode
        if event:
            now = time.monotonic()
            tid = threading.get_ident()
            self._last_event = now
            self._thread_last[tid] = now
            # a live event re-arms this thread's stall episode HERE, not
            # just in the watchdog poll: a thread that recovers and exits
            # within one poll interval must not leave its (recyclable)
            # ident marked already-dumped forever
            self._stalled_dumped.discard(tid)
        job = self.job()
        if job is not None and "job" not in rec:
            rec = {"job": job, **rec}
        rec = {"t": round(time.time(), 3), "tid": threading.get_ident(), **rec}
        for fn in list(self._listeners):
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — a tap must not break tracing
                pass
        fd = self._fd
        if fd is None:
            return
        try:
            line = json.dumps(rec, default=str) + "\n"
        except (TypeError, ValueError):
            line = json.dumps({"t": rec.get("t"), "ev": "unserializable"}) + "\n"
        try:
            # ONE os.write on an O_APPEND fd: atomic for regular files, and
            # already in the page cache when a SIGKILL lands — the crash
            # contract the kill-test pins
            os.write(fd, line.encode())
            with self._lock:
                self._records += 1
        except OSError:
            pass

    # ----- watchdog ---------------------------------------------------------

    def _active(self) -> dict[int, list[dict]]:
        out: dict[int, list[dict]] = {}
        with self._lock:
            stacks = dict(self._stacks)
        for tid, st in stacks.items():
            entries = []
            for s in list(st):
                # attrs are mutated lock-free by the owning thread (a
                # heartbeat's first insertion resizes the dict); a racing
                # copy may raise — retry once, then settle for the path
                for _ in range(2):
                    try:
                        attrs = dict(s.attrs)
                        break
                    except RuntimeError:
                        attrs = {}
                entries.append(
                    {"span": s.path, **({"attrs": attrs} if attrs else {})}
                )
            if entries:
                out[tid] = entries
        return out

    def _watch(self) -> None:
        stop = self._watchdog_stop
        while stop is not None and not stop.wait(
            min(max(self._watchdog_s / 4.0, 0.05), 1.0)
        ):
            if self._watchdog_s <= 0:
                return
            try:
                # per-thread stall detection: a thread is stalled when ITS
                # last event is old — global liveness would let a healthy
                # Ping span every minute mask a Propose worker wedged in a
                # 17-minute compile (the exact failure this exists for).
                # One dump per thread per stall episode; a thread's next
                # event clears it for re-arming.
                now = time.monotonic()
                active = self._active()
                stalled = {}
                for tid in active:
                    idle = now - self._thread_last.get(
                        tid, self._last_event
                    )
                    if idle >= self._watchdog_s:
                        stalled[tid] = idle
                    else:
                        self._stalled_dumped.discard(tid)
                fresh = {
                    tid: idle for tid, idle in stalled.items()
                    if tid not in self._stalled_dumped
                }
                if not fresh:
                    continue
                self._stalled_dumped.update(fresh)
                self._dump_stall(
                    max(fresh.values()),
                    {tid: active[tid] for tid in stalled},
                )
            except Exception:  # noqa: BLE001 — the watchdog thread must
                # survive anything (an escaped exception would silently
                # kill stall detection for the rest of the process)
                pass

    @staticmethod
    def _thread_stacks() -> dict[str, list[str]]:
        """All-thread stack dump, trimmed to the innermost 12 frames —
        shared by watchdog stall dumps and the REST threads=true view."""
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        return {
            f"{names.get(tid, '?')}:{tid}": [
                ln.rstrip() for ln in traceback.format_stack(frame)[-12:]
            ]
            for tid, frame in frames.items()
        }

    def _dump_stall(self, idle_s: float, stalled: dict) -> None:
        threads = self._thread_stacks()
        snap = _compile_snapshot()
        attr = None
        try:
            from ccx.common import compilestats

            attr = compilestats.attribution() or None
        except Exception:  # noqa: BLE001
            pass
        rec = {
            "ev": "watchdog", "stalled_s": round(idle_s, 1),
            "spans": {str(k): v for k, v in stalled.items()},
            **({"compile": snap} if snap else {}),
            **({"compileAttribution": attr} if attr else {}),
            "threads": threads,
        }
        with self._lock:
            self._watchdog_dumps += 1
        self._record(rec, event=False)
        print(
            f"[ccx-watchdog] no span event for {idle_s:.0f}s; stalled "
            "spans: "
            + "; ".join(
                s[-1]["span"] for s in stalled.values()
            ),
            file=sys.stderr, flush=True,
        )

    # ----- export -----------------------------------------------------------

    def last_tree(self) -> dict | None:
        """Most recent completed ROOT span tree (any thread)."""
        with self._lock:
            return self._last_root

    def recorder_state(self) -> dict:
        with self._lock:
            return {
                "armed": self._fd is not None,
                "path": self._path,
                "records": self._records,
            }

    def observability_summary(self) -> dict:
        """VIEWER-safe subset for ``AnalyzerState.observability``: arming /
        watchdog / sync state plus the last completed span tree (same
        sensitivity as the viewer-visible proposal result's ``spanTree``),
        WITHOUT the recorder's server filesystem path or live span/thread
        stacks — those are USER-gated on the /observability endpoint."""
        state = self.recorder_state()
        return {
            "flightRecorder": {
                "armed": state["armed"], "records": state["records"],
            },
            "watchdogSeconds": self._watchdog_s,
            "watchdogDumps": self._watchdog_dumps,
            "traceSync": self.sync,
            "lastSpanTree": self.last_tree(),
            # last heartbeat energy per job (compact, stack-free — the
            # full per-job timeline is USER-gated on /observability)
            "convergence": self.convergence_summary(),
        }

    def observability_json(self, threads: bool = False) -> dict:
        """The REST observability block (AnalyzerState.observability and
        the /observability endpoint): recorder + watchdog state, live span
        stacks, the last completed span tree, live compile counters —
        everything an operator needs to see INTO a wedged run."""
        out = {
            "flightRecorder": self.recorder_state(),
            "watchdogSeconds": self._watchdog_s,
            "watchdogDumps": self._watchdog_dumps,
            "traceSync": self.sync,
            "activeSpans": {
                str(k): v for k, v in self._active().items()
            },
            "lastSpanTree": self.last_tree(),
            # per-job convergence timeline (ISSUE 9): the last N chunk
            # heartbeat energies per active job — live quality trajectory
            # of every in-flight proposal, readable DURING a wedge
            "convergence": self.convergence_timeline(),
        }
        snap = _compile_snapshot()
        if snap is not None:
            out["compile"] = snap
            try:
                from ccx.common import compilestats

                out["compileAttribution"] = compilestats.attribution()
            except Exception:  # noqa: BLE001
                pass
        try:
            from ccx.common import costmodel

            # the cost observatory's ledger (captured per-program XLA
            # cost/memory records + device roofline spec): the flight
            # deck's quantitative half
            out["costModel"] = costmodel.summary()
        except Exception:  # noqa: BLE001
            pass
        if threads:
            out["threads"] = self._thread_stacks()
        return out


#: the process-wide tracer (one flight recorder per process, like the one
#: MetricRegistry — sidecar worker threads and the facade share it)
TRACER = Tracer()


def summarize(path: str) -> dict:
    """Parse a flight-recorder JSONL into a dead-window diagnosis: last
    record (phase/chunk/compile at death), open spans never closed,
    watchdog dumps, and — when the convergence taps streamed heartbeat
    energies — the last-known energy + plateau chunk per span open at
    death, so the diagnosis prices QUALITY as well as phase. Tolerates a
    torn final line (truncated write)."""
    records: list[dict] = []
    torn = 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                torn += 1
    # segment at "arm" records: a shared campaign JSONL holds several
    # processes' runs back to back, and a later healthy run's end records
    # must not cancel an earlier crashed run's open spans — each segment
    # keeps its own open-span ledger (the crashed rung's diagnosis is the
    # whole point of the file)
    segments: list[tuple[object, dict]] = []
    cur_pid: object = None
    cur_open: dict[str, dict] = {}
    started = False
    last_chunk: dict | None = None
    watchdogs = []
    #: span path -> most recent end record's cost block (any segment): a
    #: completed run of the same phase earlier in the file — the prewarm
    #: or cold pass — prices what an open-at-death span was expected to
    #: cost (device seconds + HBM watermark, ccx.common.costmodel)
    last_cost: dict[str, dict] = {}
    #: episode id -> joined healing arc (detected/fired/recovered spans
    #: from the ``healing`` records; ISSUE 20) — NOT segment-scoped:
    #: a soak run's episodes are the diagnosis even when a later rung
    #: appended its own segment to the shared campaign file
    healing: dict[object, dict] = {}
    healing_events = 0
    healing_forecasts = 0
    #: span path -> heartbeat-energy series of the CURRENT segment (reset
    #: on arm, like the open-span ledger): the convergence-tap trace the
    #: plateau detection below runs on
    energy_series: dict[str, list] = {}
    energy_last: dict[str, dict] = {}
    for r in records:
        ev = r.get("ev")
        if ev == "arm":
            if started:
                segments.append((cur_pid, cur_open))
            cur_pid, cur_open, started = r.get("pid"), {}, True
            energy_series, energy_last = {}, dict(energy_last)
        elif ev == "start":
            started = True
            cur_open[r.get("span", "?")] = r
        elif ev == "end":
            cur_open.pop(r.get("span", "?"), None)
            if r.get("cost"):
                last_cost[r.get("span", "?")] = r["cost"]
        elif ev == "chunk":
            last_chunk = r
            if r.get("energy") is not None:
                span = r.get("span", "?")
                energy_series.setdefault(span, []).append(r["energy"])
                energy_last[span] = {
                    "energy": r["energy"], "chunk": r.get("chunk"),
                }
        elif ev == "watchdog":
            watchdogs.append(r)
        elif ev == "healing":
            healing_events += 1
            eid = r.get("episode")
            if eid is None:
                # advisory phases (forecast prewarms) carry no episode
                # id — count them, never join them into an arc that
                # would render as an UNRECOVERED episode
                healing_forecasts += 1
            else:
                arc = healing.setdefault(eid, {"episode": eid})
                phase = r.get("phase", "?")
                arc[phase + "T"] = r.get("t")
                for k in ("cluster", "family", "cause", "verb",
                          "timeToHealS", "error"):
                    if r.get(k) is not None:
                        arc[k] = r[k]
                arc.setdefault("phases", []).append(phase)
    segments.append((cur_pid, cur_open))
    multi = len(segments) > 1
    open_spans = sorted(
        f"pid={pid} {span}" if multi and pid is not None else span
        for pid, opens in segments for span in opens
    )
    expected_cost = {
        span: last_cost[span]
        for pid, opens in segments for span in opens
        if span in last_cost
    }
    # last-known energy + plateau chunk for spans open at death — "the
    # anneal died at chunk 7, energy 212, flat since chunk 4" readout
    from ccx.common.convergence import plateau_chunk as _plateau

    convergence = {}
    for pid, opens in segments:
        for span in opens:
            if span not in energy_last:
                continue
            entry = dict(energy_last[span])
            series = energy_series.get(span) or []
            if len(series) > 1:
                entry["plateauChunk"] = _plateau(series)
                entry["chunksSeen"] = len(series)
            convergence[span] = entry
    return {
        "records": len(records),
        "runs": len(segments),
        "tornLines": torn,
        "last": records[-1] if records else None,
        "lastChunk": last_chunk,
        "openSpans": open_spans,
        # expected device time + HBM watermark for spans open at death,
        # priced from the same phase's last completed run in this file
        "expectedCost": expected_cost,
        # last-known heartbeat energy (+ plateau) for spans open at death
        "convergence": convergence,
        "watchdogDumps": len(watchdogs),
        "lastWatchdog": watchdogs[-1] if watchdogs else None,
        # healing-event timeline (ISSUE 20): detected/fired/recovered
        # spans joined per episode — a dead soak run's recording names
        # the episode in progress (detected or fired, never recovered)
        "healing": {
            "events": healing_events,
            "forecasts": healing_forecasts,
            "episodes": list(healing.values()),
            "openEpisodes": [
                arc for arc in healing.values()
                if "recovered" not in arc.get("phases", ())
            ],
        },
    }


def render_summary(s: dict) -> str:
    """Human-readable diagnosis of a ``summarize()`` dict (the default
    CLI output; ``--json`` keeps the machine form for tooling)."""
    lines = [
        f"flight recording: {s['records']} records, {s['runs']} run(s), "
        f"{s['tornLines']} torn line(s)"
    ]
    last = s.get("last")
    if last:
        lines.append("last record: " + json.dumps(last, default=str))
    lc = s.get("lastChunk")
    if lc:
        extra = (
            f" energy={lc['energy']}" if lc.get("energy") is not None else ""
        )
        lines.append(
            f"last chunk: {lc.get('span', '?')} chunk {lc.get('chunk')}"
            f"/{lc.get('total', '?')}{extra}"
        )
    if s.get("openSpans"):
        lines.append("open spans at death:")
        for span in s["openSpans"]:
            parts = [f"  {span}"]
            conv = (s.get("convergence") or {}).get(span.split(" ")[-1])
            if conv:
                parts.append(
                    f"— last energy {conv['energy']} @ chunk "
                    f"{conv.get('chunk')}"
                )
                if conv.get("plateauChunk") is not None:
                    parts.append(
                        f"(plateau at chunk {conv['plateauChunk']} of "
                        f"{conv['chunksSeen']} seen)"
                    )
            cost = (s.get("expectedCost") or {}).get(span.split(" ")[-1])
            if cost:
                parts.append(f"expected cost {json.dumps(cost)}")
            lines.append(" ".join(parts))
    else:
        lines.append("open spans at death: none (clean exit)")
    lines.append(
        f"watchdog dumps: {s['watchdogDumps']}"
        + (
            f" (last: {json.dumps(s['lastWatchdog'].get('spans', {}))})"
            if s.get("lastWatchdog")
            else ""
        )
    )
    healing = s.get("healing") or {}
    episodes = healing.get("episodes") or []
    if episodes:
        fc = healing.get("forecasts") or 0
        lines.append(
            f"healing timeline: {len(episodes)} episode(s), "
            f"{len(healing.get('openEpisodes') or [])} open at death"
            + (f", {fc} forecast prewarm(s)" if fc else "")
        )
        for arc in episodes:
            phases = arc.get("phases", [])
            parts = [
                f"  episode {arc.get('episode')} "
                f"[{arc.get('family', '?')}] {arc.get('cluster', '?')}:"
            ]
            for ph in ("detected", "fired", "recovered"):
                if ph in phases:
                    t = arc.get(ph + "T")
                    parts.append(
                        f"{ph}@{t}" if t is not None else ph
                    )
            if arc.get("verb"):
                parts.append(f"verb={arc['verb']}")
            if arc.get("timeToHealS") is not None:
                parts.append(f"tth={arc['timeToHealS']}s")
            if arc.get("cause"):
                parts.append(f"cause={arc['cause']!r}")
            if "recovered" not in phases:
                parts.append("UNRECOVERED")
            lines.append(" ".join(parts))
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m ccx.common.tracing recording.jsonl [--json]`` — print
    the diagnosis of a (possibly dead) run's flight recording: human-
    readable by default, ``--json`` for tooling (the budget advisor and
    campaign scripts consume the machine form)."""
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if len(args) != 1 or args[0] in ("-h", "--help"):
        print(
            "usage: python -m ccx.common.tracing <recording.jsonl> [--json]",
            file=sys.stderr,
        )
        return 2
    if not os.path.exists(args[0]):
        print(f"no such recording: {args[0]}", file=sys.stderr)
        return 2
    s = summarize(args[0])
    print(json.dumps(s, indent=1) if as_json else render_summary(s))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
