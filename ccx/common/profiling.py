"""Device-side profiling hooks (SURVEY.md §5.1 rebuild note).

The reference's tracing story is host-side (OperationProgress steps +
Dropwizard/JMX timers, ref async/progress/OperationProgress.java); this
module adds the TPU-native half the survey calls for: ``jax.profiler``
traces viewable in XProf/TensorBoard, with named phase annotations so the
optimizer's repair/anneal/polish phases are visible on the device timeline.

Usage:
* ``with annotate("ccx:anneal"): ...`` — cheap named region; only recorded
  while a trace is active, safe to leave on in production.
* ``with trace(log_dir): ...`` — capture a device trace for the enclosed
  block (facade wires this to the ``optimizer.profile.dir`` config key).
"""

from __future__ import annotations

import contextlib
import threading

#: serializes start/stop — jax.profiler supports one active trace per process
_LOCK = threading.Lock()
_ACTIVE = False


@contextlib.contextmanager
def annotate(name: str):
    """Named region on the device-side profiler timeline (XProf TraceMe)."""
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op if falsy or if a
    trace is already active — nested requests must not kill the outer one).

    Captures are noted in the flight recorder (ccx.common.tracing) so a
    recording cross-references the XProf artifact covering the same wall
    window — "which device trace shows this stalled chunk" is answerable
    from the JSONL alone."""
    global _ACTIVE
    if not log_dir:
        yield False
        return
    import jax.profiler

    from ccx.common.tracing import TRACER

    with _LOCK:
        if _ACTIVE:
            started = False
        else:
            jax.profiler.start_trace(log_dir)
            _ACTIVE = started = True
    try:
        if started:
            TRACER._record({"ev": "xprof-start", "dir": log_dir})
        yield started
    finally:
        if started:
            with _LOCK:
                try:
                    jax.profiler.stop_trace()
                finally:
                    _ACTIVE = False
            TRACER._record({"ev": "xprof-stop", "dir": log_dir})
