"""Compile-cache observability — counts XLA compiles and persistent-cache
hits as they happen.

The T1 budget only holds while every B5-shape program is served from the
jit cache (in-process) or the persistent ``.jax_cache/`` (cold process):
one silent recompile of the SA chunk or the greedy while_loop costs minutes
on TPU and invalidates the phase math (docs/perf-notes.md "the T1 chase";
round-4 window: a polish compile >17 min). JAX already emits monitoring
events for exactly these transitions; this module turns them into counters
the bench can difference around each phase, so BENCH_r*.json records
cache hit-ness per rung and tests/test_bench_contract.py can assert the
warm run performed ZERO fresh compiles.

Counters (cumulative since listener registration):

* ``backend_compiles`` / ``backend_compile_secs`` — actual XLA backend
  compiles in this process (``/jax/core/compile/backend_compile_duration``).
  Fires whether or not any cache is configured; a warm in-process rerun of
  an already-traced program fires nothing.
* ``persistent_hits`` — programs LOADED from the persistent compilation
  cache (``/jax/compilation_cache/cache_hits``): a process-cold but
  disk-warm path — no fresh compile paid.
* ``persistent_misses`` — fresh compiles WRITTEN to the persistent cache
  (``/jax/compilation_cache/cache_misses``): the cold path; each of these
  was a real compile the next process avoids. Entries below the
  min-compile-time/size thresholds never count.

Listeners are registered once per process, lazily at first ``snapshot()``;
``jax.monitoring`` fans events out to every listener, so coexisting
observers are unaffected. Thread-safe: events may fire from any thread
(the gRPC sidecar compiles in worker threads), so counters take a lock.

Per-label attribution (round 8): ``attributed(label)`` wraps a code region
and charges every compile that fires inside it — count AND wall-seconds —
to ``label``; ``attribution()`` returns the accumulated ledger. This is
what turns "the prewarm paid 74 s of compile" into "the full-rung SA chunk
cost 41 s, the polish chunk 9 s, ..." on the BENCH line, so a TPU window
knows exactly where its compile budget went (and which shape to cut when
one outgrows the window). Deltas are snapshot-based, so nested or
concurrent regions double-charge — attribute from ONE thread at a time
(the bench prewarm loop is sequential by construction).
"""

from __future__ import annotations

import contextlib
import threading
import time

_COUNTS = {
    "backend_compiles": 0,
    "backend_compile_secs": 0.0,
    "persistent_hits": 0,
    "persistent_misses": 0,
}
_ATTR: dict = {}
_LOCK = threading.Lock()
_REGISTERED = False

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_event(event: str, **kwargs) -> None:
    if event == _CACHE_HIT_EVENT:
        with _LOCK:
            _COUNTS["persistent_hits"] += 1
    elif event == _CACHE_MISS_EVENT:
        with _LOCK:
            _COUNTS["persistent_misses"] += 1


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event == _BACKEND_COMPILE_EVENT:
        with _LOCK:
            _COUNTS["backend_compiles"] += 1
            _COUNTS["backend_compile_secs"] += float(duration)


def _ensure_registered() -> None:
    global _REGISTERED
    import jax.monitoring

    # registration is idempotent at the module level only — the lock makes
    # the check-then-register atomic so two threads taking their first
    # snapshot() concurrently (bench main thread + a sidecar worker) can
    # never double-register and double-count every compile
    with _LOCK:
        if _REGISTERED:
            return
        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _REGISTERED = True


def snapshot() -> dict:
    """Cumulative counters so far (registers the listeners on first use —
    call once early so no compile predates the listeners)."""
    _ensure_registered()
    with _LOCK:
        return dict(_COUNTS)


def delta(before: dict, after: dict) -> dict:
    """Counter difference between two snapshots, rounded for JSON."""
    d = {k: after[k] - before[k] for k in _COUNTS}
    d["backend_compile_secs"] = round(d["backend_compile_secs"], 2)
    return d


@contextlib.contextmanager
def attributed(label: str):
    """Charge every compile fired inside the region to ``label`` (summed
    across re-entries), plus the region's wall seconds — the per-shape
    compile ledger the bench prewarm emits (module docstring)."""
    before = snapshot()
    t0 = time.monotonic()
    try:
        yield
    finally:
        d = delta(before, snapshot())
        wall = time.monotonic() - t0
        with _LOCK:
            slot = _ATTR.setdefault(
                label, {**{k: 0 for k in _COUNTS},
                        "backend_compile_secs": 0.0, "wall_secs": 0.0}
            )
            for k in _COUNTS:
                slot[k] += d[k]
            slot["backend_compile_secs"] = round(
                slot["backend_compile_secs"], 2
            )
            slot["wall_secs"] = round(slot["wall_secs"] + wall, 2)


def attribution() -> dict:
    """The per-label compile ledger accumulated so far (label -> counter
    dict + wall_secs)."""
    with _LOCK:
        return {k: dict(v) for k, v in _ATTR.items()}


def export_gauges(registry=None) -> None:
    """Register the live counters as gauges on the metrics registry so an
    operator can watch compile activity DURING a wedged run from /metrics
    (a climbing ``backend_compile_secs`` under a flat heartbeat is the
    ">17-min compile" signature — docs/observability.md). Idempotent:
    re-registration just replaces the gauge callables. The gauge reads go
    through ``snapshot()``, so the first scrape also installs the
    jax.monitoring listeners."""
    if registry is None:
        from ccx.common.metrics import REGISTRY as registry  # noqa: N811
    docs = {
        "backend_compiles": "fresh XLA backend compiles in this process",
        "backend_compile_secs": "wall seconds spent in XLA backend compiles",
        "persistent_hits": "programs loaded from the persistent compile cache",
        "persistent_misses": "fresh compiles written to the persistent cache",
    }
    for key in _COUNTS:
        registry.gauge(
            f"compile-{key.replace('_', '-')}",
            (lambda k=key: snapshot()[k]),
            help=docs.get(key, key),
        )
