"""Deterministic fault injection — the chaos layer (ISSUE 12).

Rounds 8–15 turned the sidecar into a stateful serving system (fleet
scheduler, device-resident ``SnapshotRegistry``, ``PlacementStore`` warm
bases, delta grafts, streamed result frames) whose failure paths had never
been exercised. This module is the seam registry every one of those paths
threads through: a **seeded, schedule-driven** fault injector with named
seams, armed explicitly (``CCX_FAULTS`` env / ``observability.faults.*``
config / programmatic :meth:`FaultRegistry.arm`) and a no-op otherwise.

Design rules (the ``CCX_CONVERGENCE=0`` contract, applied to chaos):

* **Disarmed is free and bit-exact.** Every call site guards with
  ``if FAULTS.armed:`` — one attribute read, no function call, no import
  side effects. Disarmed, the serving path traces/compiles/executes
  bit-identically to a tree without this module (tripwire-pinned by
  ``tests/test_faults.py``).
* **Deterministic.** A schedule names WHICH hit of a seam fires (the Nth,
  every Mth from N, or every hit); the corrupt action derives its bytes
  from a seeded RNG keyed by (seed, seam, hit index). Same spec + seed ⇒
  the same faults in the same places, so a chaos failure reproduces.
* **Faults are data, not control flow.** A seam raises
  :class:`InjectedFault` (optionally flavored: ``resource-exhausted`` to
  exercise HBM-pressure degradation, ``sever`` to kill a stream without
  an error frame), sleeps, or corrupts a payload — the REAL recovery code
  downstream handles it exactly as it would handle the organic fault.

Seams (the serving stack's failure surface — docs/architecture.md
"Failure semantics" documents what each one degrades to):

=====================  ======================================================
``snapshot.transfer``  host→device model build/transfer
                       (``SnapshotRegistry.model``)
``registry.graft``     metric-delta graft onto the resident device model
                       (``SnapshotRegistry.put``)
``placement.bank``     warm-base banking into the ``PlacementStore``
                       (``incremental.remember``)
``device.diff``        the compiled columnar diff (``proposals.
                       columnar_diff``)
``rpc.frame``          every Propose stream frame at the gRPC edge
                       (``server.propose_stream``)
``scheduler.grant``    chunk-dispatch grant acquisition
                       (``ChunkScheduler.chunk``)
``compile``            cold-pipeline entry (``optimizer._optimize``) — the
                       stand-in for a failed/wedged XLA compile
=====================  ======================================================

Spec grammar (``;``-separated rules)::

    <seam>:<action>@<schedule>[:<param>=<value>,...]

    action    raise | exhaust | sever | delay | corrupt
    schedule  N        fire on the Nth hit only (1-based)
              N+       fire on every hit from the Nth on
              N/M      fire on hit N, N+M, N+2M, ...
              *        fire on every hit
    params    delay=<seconds>   (delay action; default 0.05)

Examples::

    CCX_FAULTS="registry.graft:raise@2"
    CCX_FAULTS="rpc.frame:sever@3;snapshot.transfer:exhaust@1"
    CCX_FAULTS="rpc.frame:corrupt@2/5;scheduler.grant:raise@1"

Dependency-light on purpose: stdlib only — the seams live in modules that
must import instantly (wire client, scheduler).
"""

from __future__ import annotations

import os
import random
import threading
import time

#: env arming (the bench/tools path — config ``observability.faults.spec``
#: is the embedded-service twin)
ENV_FAULTS = "CCX_FAULTS"
ENV_FAULTS_SEED = "CCX_FAULTS_SEED"

#: the named seams — ``arm()`` rejects a rule naming anything else, so a
#: typo'd chaos spec fails loudly instead of silently injecting nothing
SEAMS = frozenset({
    "snapshot.transfer",
    "registry.graft",
    "placement.bank",
    "device.diff",
    "rpc.frame",
    "scheduler.grant",
    "compile",
})

ACTIONS = frozenset({"raise", "exhaust", "sever", "delay", "corrupt"})


class InjectedFault(RuntimeError):
    """A fault fired by the registry. ``seam``/``action``/``hit`` name the
    rule; ``kind`` flavors the raise so recovery code can branch the same
    way it branches on the organic error:

    * ``"resource-exhausted"`` — stands in for an XLA RESOURCE_EXHAUSTED
      allocation failure (HBM pressure); the snapshot registry degrades
      by evicting device residents and retrying cold.
    * ``"sever"`` — the transport died mid-stream; the gRPC edge ends the
      stream abruptly (no error frame), the client sees a truncated
      stream and restarts it.
    * ``"injected"`` — a generic failure of the seam's operation.
    """

    def __init__(self, seam: str, action: str, hit: int,
                 kind: str = "injected") -> None:
        super().__init__(
            f"injected fault: {seam} {action} (hit {hit})"
        )
        self.seam = seam
        self.action = action
        self.hit = hit
        self.kind = kind


class FaultRule:
    """One parsed spec rule (see module docstring for the grammar)."""

    __slots__ = ("seam", "action", "start", "every", "once", "delay_s")

    def __init__(self, seam: str, action: str, start: int, every: int,
                 once: bool, delay_s: float) -> None:
        self.seam = seam
        self.action = action
        self.start = start      # first firing hit (1-based)
        self.every = every      # period (0 with once=True: single shot)
        self.once = once
        self.delay_s = delay_s

    def fires(self, hit: int) -> bool:
        if hit < self.start:
            return False
        if self.once:
            return hit == self.start
        if self.every <= 1:
            return True
        return (hit - self.start) % self.every == 0

    def describe(self) -> str:
        if self.once:
            sched = f"@{self.start}"
        elif self.every <= 1:
            sched = f"@{self.start}+" if self.start > 1 else "@*"
        else:
            sched = f"@{self.start}/{self.every}"
        return f"{self.seam}:{self.action}{sched}"


def parse_spec(spec: str) -> list[FaultRule]:
    """Parse a spec string into rules; raises ``ValueError`` on unknown
    seams/actions or malformed schedules (a chaos run must never silently
    inject nothing)."""
    rules: list[FaultRule] = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"malformed fault rule {part!r} "
                             "(want seam:action@schedule)")
        seam = fields[0].strip()
        if seam not in SEAMS:
            raise ValueError(
                f"unknown fault seam {seam!r}; known: {sorted(SEAMS)}"
            )
        act_sched = fields[1].strip()
        if "@" in act_sched:
            action, sched = act_sched.split("@", 1)
        else:
            action, sched = act_sched, "1"
        action = action.strip()
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; known: {sorted(ACTIONS)}"
            )
        start, every, once = 1, 0, True
        sched = sched.strip()
        if sched == "*":
            start, every, once = 1, 1, False
        elif sched.endswith("+"):
            start, every, once = int(sched[:-1]), 1, False
        elif "/" in sched:
            a, m = sched.split("/", 1)
            start, every, once = int(a), max(int(m), 1), False
        else:
            start = int(sched)
        if start < 1:
            raise ValueError(f"fault schedule must be 1-based: {part!r}")
        delay_s = 0.05
        for extra in fields[2:]:
            for kv in extra.split(","):
                if not kv.strip():
                    continue
                k, _, v = kv.partition("=")
                if k.strip() == "delay":
                    delay_s = float(v)
                else:
                    raise ValueError(f"unknown fault param {k!r} in {part!r}")
        rules.append(FaultRule(seam, action, start, every, once, delay_s))
    return rules


class FaultRegistry:
    """The process-wide injector (:data:`FAULTS`). ``armed`` is a plain
    bool attribute — the one thing a disarmed hot path ever reads."""

    def __init__(self) -> None:
        self.armed = False
        self._lock = threading.Lock()
        self._rules: list[FaultRule] = []
        self._seed = 0
        #: per-seam hit counters (every pass through an armed seam)
        self._hits: dict[str, int] = {}
        #: per-(seam, action) fired counters
        self._fired: dict[str, int] = {}

    # ----- arming -----------------------------------------------------------

    def arm(self, spec, seed: int = 0) -> None:
        """Arm with a spec string (or pre-parsed rule list). Resets the
        hit counters — a schedule always counts from the arming point."""
        rules = parse_spec(spec) if isinstance(spec, str) else list(spec)
        with self._lock:
            self._rules = rules
            self._seed = int(seed)
            self._hits = {}
            self._fired = {}
            self.armed = True

    def disarm(self) -> None:
        with self._lock:
            self.armed = False
            self._rules = []
            self._hits = {}

    def arm_from_env(self) -> bool:
        """Arm from ``CCX_FAULTS`` when set (bench/standalone-sidecar
        entry points call this; embedded services use the config key).
        Returns True when armed."""
        spec = os.environ.get(ENV_FAULTS, "")
        if not spec:
            return False
        self.arm(spec, seed=int(os.environ.get(ENV_FAULTS_SEED, "0")))
        return True

    # ----- the seam hit -----------------------------------------------------

    def hit(self, seam: str, payload: bytes | None = None):
        """One pass through an armed seam. Fires the first matching rule
        for this hit index: ``raise``/``exhaust``/``sever`` raise an
        :class:`InjectedFault` (flavored), ``delay`` sleeps, ``corrupt``
        returns a deterministically corrupted copy of ``payload`` (or
        raises when there is no payload to corrupt — a corrupt rule on a
        payload-less seam is a plain failure). Returns ``payload``
        (possibly corrupted) so call sites can write
        ``buf = FAULTS.hit("rpc.frame", buf)``."""
        with self._lock:
            if not self.armed:
                return payload
            n = self._hits.get(seam, 0) + 1
            self._hits[seam] = n
            rule = None
            for r in self._rules:
                if r.seam == seam and r.fires(n):
                    rule = r
                    break
            if rule is not None:
                key = f"{rule.seam}:{rule.action}"
                self._fired[key] = self._fired.get(key, 0) + 1
                seed = self._seed
        if rule is None:
            return payload
        if rule.action == "delay":
            time.sleep(rule.delay_s)
            return payload
        if rule.action == "corrupt":
            if payload is None:
                raise InjectedFault(seam, "corrupt", n)
            return _corrupt(bytes(payload), seed, seam, n)
        kind = {
            "exhaust": "resource-exhausted",
            "sever": "sever",
        }.get(rule.action, "injected")
        raise InjectedFault(seam, rule.action, n, kind=kind)

    # ----- accounting -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "armed": self.armed,
                "rules": [r.describe() for r in self._rules],
                "seed": self._seed,
                "hits": dict(self._hits),
                "fired": dict(self._fired),
            }

    def fired_total(self) -> int:
        with self._lock:
            return sum(self._fired.values())

    def hits_total(self) -> int:
        with self._lock:
            return sum(self._hits.values())


def _corrupt(buf: bytes, seed: int, seam: str, hit: int) -> bytes:
    """Deterministically flip a handful of bytes: same (seed, seam, hit)
    ⇒ same corruption. Empty payloads gain one garbage byte so the
    corruption is never a silent no-op."""
    if not buf:
        return b"\xff"
    import zlib

    # process-stable derivation (tuple/str seeding hashes with the
    # per-process salt — NOT reproducible across runs)
    rng = random.Random(
        (int(seed) * 1_000_003 + int(hit)) ^ zlib.crc32(seam.encode())
    )
    out = bytearray(buf)
    for _ in range(max(1, min(4, len(out) // 64))):
        i = rng.randrange(len(out))
        out[i] ^= 1 + rng.randrange(255)
    return bytes(out)


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for an HBM-pressure allocation failure — injected
    (:class:`InjectedFault` flavored ``resource-exhausted``) or organic
    (XLA's ``RESOURCE_EXHAUSTED`` runtime error). The snapshot registry
    branches on this to evict-and-retry-cold instead of failing the RPC."""
    if isinstance(exc, InjectedFault):
        return exc.kind == "resource-exhausted"
    return "RESOURCE_EXHAUSTED" in str(exc)


#: the process-wide registry (one per process, like scheduler.FLEET and
#: the tracer); armed from CCX_FAULTS by bench/sidecar entry points or
#: the observability.faults.spec config key, never implicitly
FAULTS = FaultRegistry()
