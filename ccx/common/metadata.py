"""Cluster metadata snapshot — the topology side of model generation.

Parity: the reference builds its ``ClusterModel`` from two inputs (SURVEY.md
call stack 3.2): the Kafka **metadata** (topics, partition replica lists,
leaders, rack ids, liveness — via AdminClient/MetadataClient) and the
aggregated **load samples**. This module is the metadata half: an immutable
snapshot type produced by the admin layer (``ccx.executor.admin``) and
consumed by the LoadMonitor, plus the dense partition indexing every tensor
shares (``ModelGeneration`` pins a snapshot to an optimizer run so the JVM↔
sidecar exchange stays consistent, SURVEY.md §5.2).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True, order=True)
class TopicPartition:
    topic: str
    partition: int

    def __str__(self) -> str:
        return f"{self.topic}-{self.partition}"


@dataclasses.dataclass(frozen=True)
class PartitionInfo:
    tp: TopicPartition
    replicas: tuple[int, ...]      # broker ids, index 0 = preferred leader
    leader: int                    # broker id, -1 = offline
    replica_dirs: tuple[int, ...] = ()   # log-dir (disk) index per replica


@dataclasses.dataclass(frozen=True)
class BrokerInfo:
    broker_id: int
    rack: str
    alive: bool = True
    num_disks: int = 1
    offline_disks: tuple[int, ...] = ()
    #: hostname (ref model/Host.java: rack -> host -> broker; several
    #: brokers may share a host). "" = unknown -> the broker is its own
    #: host. When rack is ALSO unknown, rack-awareness falls back to host
    #: distinctness (upstream ClusterModel.createBroker semantics).
    host: str = ""

    def rack_key(self) -> str:
        """Effective rack grouping key: rack, else host, else broker id."""
        return self.rack or self.host or f"broker-{self.broker_id}"

    def host_key(self) -> str:
        """Effective host grouping key: host, else broker id."""
        return self.host or f"broker-{self.broker_id}"


@dataclasses.dataclass(frozen=True)
class ClusterMetadata:
    """One generation of cluster topology (ref ModelGeneration + Cluster)."""

    generation: int
    brokers: tuple[BrokerInfo, ...]
    partitions: tuple[PartitionInfo, ...]

    # ----- dense indexing ---------------------------------------------------

    def broker_ids(self) -> list[int]:
        return [b.broker_id for b in self.brokers]

    def broker_index(self) -> dict[int, int]:
        """broker id -> dense row (tensor broker axis)."""
        return {b.broker_id: i for i, b in enumerate(self.brokers)}

    def partition_index(self) -> dict[TopicPartition, int]:
        """TopicPartition -> dense row (tensor partition axis), sorted so the
        index is stable for a given topic set (generation-independent for
        unchanged topology)."""
        return {p.tp: i for i, p in enumerate(self.partitions)}

    def topics(self) -> list[str]:
        seen: dict[str, None] = {}
        for p in self.partitions:
            seen.setdefault(p.tp.topic, None)
        return list(seen)

    def topic_index(self) -> dict[str, int]:
        return {t: i for i, t in enumerate(self.topics())}

    def rack_keys(self) -> list[str]:
        """Distinct effective rack keys (rack || host || broker id)."""
        seen: dict[str, None] = {}
        for b in self.brokers:
            seen.setdefault(b.rack_key(), None)
        return list(seen)

    def hosts(self) -> list[str]:
        """Distinct effective host keys."""
        seen: dict[str, None] = {}
        for b in self.brokers:
            seen.setdefault(b.host_key(), None)
        return list(seen)

    def alive_broker_ids(self) -> set[int]:
        return {b.broker_id for b in self.brokers if b.alive}

    def dead_broker_ids(self) -> set[int]:
        return {b.broker_id for b in self.brokers if not b.alive}

    def partitions_of(self, topic: str) -> list[PartitionInfo]:
        return [p for p in self.partitions if p.tp.topic == topic]

    def replica_count(self) -> int:
        return sum(len(p.replicas) for p in self.partitions)

    def under_replicated(self, target_rf: dict[str, int] | None = None) -> list[PartitionInfo]:
        """Partitions whose live replica count is below their RF (URP) —
        consumed by movement strategies and the topic-anomaly finder.
        ``target_rf`` overrides the required count per topic (topic-anomaly
        RF checks)."""
        alive = self.alive_broker_ids()
        target_rf = target_rf or {}
        return [
            p for p in self.partitions
            if sum(1 for b in p.replicas if b in alive)
            < target_rf.get(p.tp.topic, len(p.replicas))
        ]
