"""Service entry point.

Parity: ``KafkaCruiseControlMain.java`` (SURVEY.md C22, call stack 3.1):
parse the properties file, build the façade (monitor → analyzer → executor →
detector), start the REST server, serve until interrupted.

Usage::

    python -m ccx [config/cruisecontrol.properties] [port] [hostname]

With the default simulated admin client this boots a self-contained demo
cluster (brokers/topics from ``demo.*`` keys) — the standalone mode used by
benchmarks and integration tests; pointing ``admin.client.class`` at a real
cluster adapter is the production path.
"""

from __future__ import annotations

import logging
import signal
import sys

from ccx.config import CruiseControlConfig
from ccx.common.device import ensure_responsive_backend
from ccx.servlet.server import CruiseControlApp
from ccx.service.facade import CruiseControl


def build_demo_admin(n_brokers: int = 6, n_racks: int = 3,
                     topics: tuple[tuple[str, int, int], ...] = (
                         ("demo-a", 32, 2), ("demo-b", 16, 3)
                     )):
    from ccx.executor.admin import SimulatedAdminClient, SimulatedCluster

    sim = SimulatedCluster()
    for b in range(n_brokers):
        sim.add_broker(b, rack=f"rack-{b % n_racks}", num_disks=2)
    for name, parts, rf in topics:
        sim.create_topic(name, parts, rf)
    return SimulatedAdminClient(sim)


def main(argv: list[str] | None = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    # Operator backend override (CCX_JAX_PLATFORM=cpu) or, absent one, a
    # wedged-accelerator probe with CPU fallback — without this the service
    # would boot, serve /state, and then hang every optimizer verb on first
    # backend use (ccx.common.device docstring).
    ensure_responsive_backend()
    if argv:
        cfg = CruiseControlConfig.from_properties_file(argv[0])
    else:
        cfg = CruiseControlConfig(
            {
                "metric.sampler.class":
                    "ccx.monitor.sampling.sampler.SyntheticMetricSampler",
                "broker.capacity.config.resolver.class":
                    "ccx.monitor.capacity.StaticCapacityResolver",
                "metric.sampling.interval.ms": 5000,
                "partition.metrics.window.ms": 10_000,
                "num.partition.metrics.windows": 3,
                "broker.metrics.window.ms": 10_000,
                "num.broker.metrics.windows": 3,
            }
        )
    if len(argv) > 1:
        cfg = cfg.with_overrides(**{"webserver.http.port": int(argv[1])})
    if len(argv) > 2:
        cfg = cfg.with_overrides(**{"webserver.http.address": argv[2]})

    admin = cfg.configured_instance("admin.client.class")
    from ccx.executor.admin import SimulatedAdminClient

    if isinstance(admin, SimulatedAdminClient) and not admin.cluster._brokers:
        admin = build_demo_admin()

    facade = CruiseControl(cfg, admin=admin)
    facade.start_up()
    app = CruiseControlApp(cfg, facade)
    host, port = app.start()
    logging.info("ccx REST API listening on http://%s:%s%s", host, port,
                 "/kafkacruisecontrol/state")
    openapi_server = None
    if cfg["webserver.openapi.port"] > 0:
        from ccx.servlet.openapi_server import OpenApiServer

        openapi_server = OpenApiServer(
            app, cfg["webserver.openapi.address"],
            cfg["webserver.openapi.port"],
        )
        oa_host, oa_port = openapi_server.start()
        logging.info(
            "ccx OpenAPI surface listening on http://%s:%s%s",
            oa_host, oa_port, "/kafkacruisecontrol/openapi",
        )

    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop["flag"]:
            signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        if openapi_server is not None:
            openapi_server.stop()
        app.stop()
        facade.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
