"""Two-step review purgatory.

Parity: ``servlet/purgatory/Purgatory.java`` + Review* classes (SURVEY.md
C33): when ``two.step.verification.enabled``, mutating POSTs are parked as
PENDING_REVIEW requests; an ADMIN approves or discards them via the
``review`` endpoint; an approved request is executed by re-submitting the
original POST with its ``review_id``. ``review_board`` lists requests.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading

from ccx.common.exceptions import UserRequestException
from ccx.servlet.endpoints import EndPoint


class ReviewStatus:
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


@dataclasses.dataclass
class RequestInfo:
    review_id: int
    endpoint: EndPoint
    query: dict
    submitter: str
    submission_ms: int
    status: str = ReviewStatus.PENDING_REVIEW
    reason: str = ""

    def to_json(self) -> dict:
        return {
            "Id": self.review_id,
            "EndPoint": self.endpoint.value,
            "Status": self.status,
            "SubmitterAddress": self.submitter,
            "SubmissionTimeMs": self.submission_ms,
            "Reason": self.reason,
        }


class Purgatory:
    def __init__(self, retention_ms: int = 1_209_600_000, max_requests: int = 25,
                 clock=None) -> None:
        import time as _time

        self.retention_ms = retention_ms
        self.max_requests = max_requests
        self.clock = clock or (lambda: int(_time.time() * 1000))
        self._requests: dict[int, RequestInfo] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config, clock=None) -> "Purgatory":
        return cls(
            config["two.step.purgatory.retention.time.ms"],
            config["two.step.purgatory.max.requests"],
            clock=clock,
        )

    def submit(self, endpoint: EndPoint, query: dict, submitter: str,
               reason: str = "") -> RequestInfo:
        with self._lock:
            self._expire()
            pending = sum(
                1 for r in self._requests.values()
                if r.status == ReviewStatus.PENDING_REVIEW
            )
            if pending >= self.max_requests:
                raise UserRequestException(
                    "Purgatory is full "
                    f"(two.step.purgatory.max.requests={self.max_requests})"
                )
            info = RequestInfo(
                review_id=next(self._ids),
                endpoint=endpoint,
                query=dict(query),
                submitter=submitter,
                submission_ms=self.clock(),
                reason=reason,
            )
            self._requests[info.review_id] = info
            return info

    def review(self, approve: tuple[int, ...], discard: tuple[int, ...]) -> list[dict]:
        with self._lock:
            for rid in approve:
                info = self._require(rid)
                if info.status != ReviewStatus.PENDING_REVIEW:
                    raise UserRequestException(
                        f"Request {rid} is {info.status}, not reviewable"
                    )
                info.status = ReviewStatus.APPROVED
            for rid in discard:
                info = self._require(rid)
                if info.status == ReviewStatus.SUBMITTED:
                    raise UserRequestException(
                        f"Request {rid} already submitted"
                    )
                info.status = ReviewStatus.DISCARDED
            return [r.to_json() for r in self._requests.values()]

    def take_approved(self, review_id: int, endpoint: EndPoint) -> RequestInfo:
        """Claim an approved request for execution (marks SUBMITTED)."""
        with self._lock:
            info = self._require(review_id)
            if info.endpoint is not endpoint:
                raise UserRequestException(
                    f"Review {review_id} is for {info.endpoint.value}, "
                    f"not {endpoint.value}"
                )
            if info.status != ReviewStatus.APPROVED:
                raise UserRequestException(
                    f"Request {review_id} is {info.status}, not APPROVED"
                )
            info.status = ReviewStatus.SUBMITTED
            return info

    def board(self, review_ids: tuple[int, ...] = ()) -> list[dict]:
        with self._lock:
            self._expire()
            rs = self._requests.values()
            if review_ids:
                rs = [r for r in rs if r.review_id in review_ids]
            return [r.to_json() for r in rs]

    def _require(self, rid: int) -> RequestInfo:
        info = self._requests.get(rid)
        if info is None:
            raise UserRequestException(f"No review request with id {rid}")
        return info

    def _expire(self) -> None:
        now = self.clock()
        for rid in list(self._requests):
            if now - self._requests[rid].submission_ms > self.retention_ms:
                del self._requests[rid]
