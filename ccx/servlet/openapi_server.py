"""Second API surface: contract-routed asyncio server (ref SURVEY.md C36).

The reference's optional Vert.x module mirrors the servlet endpoints behind
an OpenAPI contract on its own server. ccx's equivalent keeps the module's
two defining properties without a second endpoint table:

* **contract-first routing** — the route/parameter table is built FROM the
  generated OpenAPI document (``ccx.servlet.openapi.openapi_document``,
  itself generated from the endpoint registry), and every request is
  validated against that contract (unknown path / method / parameter and
  type mismatches are rejected) BEFORE dispatch;
* **a genuinely different HTTP engine** — non-blocking asyncio transport
  (the Vert.x role) instead of the servlet's threading ``BaseHTTPServer``.

Both surfaces share the transport-independent
``CruiseControlApp.handle()`` (auth, two-step review, user-task replay,
verbs), so behavior cannot drift. Enabled by ``webserver.openapi.port``
(0 = disabled — the upstream module is optional too).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import urllib.parse

from ccx.common.exceptions import UserRequestException
from ccx.servlet.endpoints import EndPoint, parse_params
from ccx.servlet.security import authorized
from ccx.servlet.server import URL_PREFIX

log = logging.getLogger(__name__)

_MAX_HEADER_BYTES = 64 * 1024


class ContractViolation(Exception):
    """Request does not match the OpenAPI document."""


class OpenApiServer:
    """Asyncio HTTP server routed by the generated OpenAPI contract."""

    def __init__(self, app, address: str = "127.0.0.1", port: int = 0) -> None:
        from ccx.servlet.openapi import openapi_document

        self.app = app
        self.address = address
        self.port = port
        self.document = openapi_document(URL_PREFIX)
        # path -> method -> {param: schema}; built once from the contract
        self.routes: dict[str, dict[str, dict[str, dict]]] = {}
        for path, methods in self.document["paths"].items():
            self.routes[path] = {
                m.upper(): {
                    p["name"]: p.get("schema", {})
                    for p in spec.get("parameters", [])
                }
                for m, spec in methods.items()
            }
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server: asyncio.AbstractServer | None = None
        self._started = threading.Event()
        self._boot_error: BaseException | None = None

    # ----- lifecycle --------------------------------------------------------

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(
            target=self._run, name="ccx-openapi", daemon=True
        )
        self._thread.start()
        # a swallowed bind failure would log "listening" while nothing
        # listens — surface boot errors to the caller
        if not self._started.wait(timeout=10):
            raise RuntimeError("OpenAPI surface failed to start within 10 s")
        if self._boot_error is not None:
            raise RuntimeError(
                f"OpenAPI surface failed to bind "
                f"{self.address}:{self.port}: {self._boot_error}"
            ) from self._boot_error
        return self.address, self.port

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            # limit > _MAX_HEADER_BYTES so readuntil can actually RETURN an
            # oversized head for the 431 check instead of erroring at the
            # exact threshold
            self._server = await asyncio.start_server(
                self._client, self.address, self.port,
                limit=2 * _MAX_HEADER_BYTES,
            )
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(boot())
        except BaseException as e:  # noqa: BLE001 — reported by start()
            self._boot_error = e
            self._started.set()
            self._loop.close()
            return
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self) -> None:
        if self._loop is None:
            return

        def _shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=10)

    # ----- contract validation ---------------------------------------------

    def _validate(self, path: str, method: str, query: dict) -> EndPoint:
        methods = self.routes.get(path)
        if methods is None:
            raise ContractViolation(f"path {path} is not in the contract")
        schema = methods.get(method)
        if schema is None:
            raise ContractViolation(
                f"{path} does not support {method} (contract methods: "
                f"{sorted(methods)})"
            )
        for name, value in query.items():
            if name not in schema:
                raise ContractViolation(
                    f"parameter {name!r} is not in the contract for {path}"
                )
            typ = schema[name].get("type")
            if typ == "integer":
                try:
                    int(value)
                except ValueError:
                    raise ContractViolation(
                        f"parameter {name!r} must be an integer, got {value!r}"
                    ) from None
            elif typ == "boolean" and value.lower() not in (
                "true", "false", "1", "0", "",
            ):
                raise ContractViolation(
                    f"parameter {name!r} must be a boolean, got {value!r}"
                )
        return EndPoint(path[len(URL_PREFIX) + 1:].strip("/").lower())

    # ----- request handling -------------------------------------------------

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            writer.close()
            return
        if len(head) > _MAX_HEADER_BYTES:
            await self._send(writer, 431, {"errorMessage": "headers too large"})
            return
        try:
            lines = head.decode("latin-1").split("\r\n")
            method, target, _ = lines[0].split(" ", 2)
            headers = {}
            for ln in lines[1:]:
                if ":" in ln:
                    k, v = ln.split(":", 1)
                    headers[k.strip().lower()] = v.strip()
            length = int(headers.get("content-length") or 0)
            body = await reader.readexactly(length) if length else b""
            parsed = urllib.parse.urlparse(target)
            query = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(
                    parsed.query, keep_blank_values=True
                ).items()
            }
            if "application/x-www-form-urlencoded" in headers.get(
                "content-type", ""
            ):
                query = {
                    **{
                        k: v[-1]
                        for k, v in urllib.parse.parse_qs(
                            body.decode(errors="replace"),
                            keep_blank_values=True,
                        ).items()
                    },
                    **query,
                }
            peer = writer.get_extra_info("peername") or ("", 0)
            headers["x-ccx-peer-address"] = peer[0]

            # same authentication gate as the servlet — including for the
            # contract document itself (the servlet 401s it too)
            auth = self.app.security.authenticate(headers)
            if not auth.ok:
                await self._send(
                    writer, 401, {"errorMessage": "Authentication required"},
                    {"WWW-Authenticate": auth.challenge or "Basic"},
                )
                return
            if method == "GET" and parsed.path == URL_PREFIX + "/openapi":
                await self._send(writer, 200, self.document)
                return
            try:
                endpoint = self._validate(parsed.path, method, query)
            except ContractViolation as e:
                await self._send(writer, 400, {"errorMessage": str(e)})
                return

            if not authorized(auth.roles, endpoint):
                await self._send(
                    writer, 403,
                    {"errorMessage":
                     f"{auth.principal} is not authorized for "
                     f"{endpoint.value}"},
                )
                return
            params = parse_params(endpoint, query)
            # handle() blocks up to maxBlockTimeMs — keep the event loop free
            status, resp, extra = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self.app.handle(
                    method, endpoint, params, headers,
                    client=auth.principal or peer[0],
                ),
            )
            await self._send(writer, status, resp, extra)
        except UserRequestException as e:
            # same mapping as the servlet (400, not 500) — the async-replay
            # and parameter errors are client errors on both surfaces
            try:
                await self._send(writer, 400, {"errorMessage": str(e)})
            except Exception:  # noqa: BLE001
                writer.close()
        except Exception as e:  # noqa: BLE001 — server boundary
            log.exception("openapi request failed")
            try:
                await self._send(writer, 500, {"errorMessage": str(e)})
            except Exception:  # noqa: BLE001
                writer.close()

    async def _send(self, writer: asyncio.StreamWriter, status: int,
                    body: dict, extra: dict | None = None) -> None:
        payload = json.dumps({"version": 1, **body}).encode()
        reason = {200: "OK", 202: "Accepted", 400: "Bad Request",
                  401: "Unauthorized", 403: "Forbidden",
                  431: "Request Header Fields Too Large",
                  500: "Internal Server Error"}.get(status, "")
        head = [f"HTTP/1.1 {status} {reason}",
                "Content-Type: application/json",
                f"Content-Length: {len(payload)}",
                "Connection: close"]
        for k, v in (extra or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + payload)
        await writer.drain()
        writer.close()
