"""Endpoint registry + request parameter parsing.

Parity: ``servlet/CruiseControlEndPoint.java`` + ``servlet/parameters/``
(SURVEY.md C32): the endpoint enum with its GET/POST split, and one
parameter-spec per endpoint mapping query parameters to typed values
(booleans, csv lists, enums) with unknown-parameter rejection — the
reference returns 400 on unrecognized parameters.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from ccx.common.exceptions import UserRequestException


class EndPoint(enum.Enum):
    # GET
    STATE = "state"
    LOAD = "load"
    PARTITION_LOAD = "partition_load"
    PROPOSALS = "proposals"
    KAFKA_CLUSTER_STATE = "kafka_cluster_state"
    USER_TASKS = "user_tasks"
    REVIEW_BOARD = "review_board"
    PERMISSIONS = "permissions"
    BOOTSTRAP = "bootstrap"
    TRAIN = "train"
    OBSERVABILITY = "observability"
    # POST
    REBALANCE = "rebalance"
    ADD_BROKER = "add_broker"
    REMOVE_BROKER = "remove_broker"
    FIX_OFFLINE_REPLICAS = "fix_offline_replicas"
    DEMOTE_BROKER = "demote_broker"
    STOP_PROPOSAL_EXECUTION = "stop_proposal_execution"
    PAUSE_SAMPLING = "pause_sampling"
    RESUME_SAMPLING = "resume_sampling"
    TOPIC_CONFIGURATION = "topic_configuration"
    RIGHTSIZE = "rightsize"
    ADMIN = "admin"
    REVIEW = "review"


GET_ENDPOINTS = frozenset(
    {
        EndPoint.STATE, EndPoint.LOAD, EndPoint.PARTITION_LOAD,
        EndPoint.PROPOSALS, EndPoint.KAFKA_CLUSTER_STATE, EndPoint.USER_TASKS,
        EndPoint.REVIEW_BOARD, EndPoint.PERMISSIONS, EndPoint.BOOTSTRAP,
        EndPoint.TRAIN, EndPoint.OBSERVABILITY,
    }
)
POST_ENDPOINTS = frozenset(set(EndPoint) - GET_ENDPOINTS)

#: endpoints whose POST semantics mutate the cluster — these are the ones
#: purgatory parks when two-step verification is on (ref C33)
MUTATING_ENDPOINTS = frozenset(
    {
        EndPoint.REBALANCE, EndPoint.ADD_BROKER, EndPoint.REMOVE_BROKER,
        EndPoint.FIX_OFFLINE_REPLICAS, EndPoint.DEMOTE_BROKER,
        EndPoint.TOPIC_CONFIGURATION,
    }
)


class ParamType(enum.Enum):
    STRING = "string"
    BOOLEAN = "boolean"
    INT = "int"
    CSV_INT = "csv_int"     # "1,2,3" -> (1, 2, 3)
    CSV_STR = "csv_str"


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    name: str
    type: ParamType
    default: Any = None


_COMMON = (
    ParamSpec("json", ParamType.BOOLEAN, True),
    ParamSpec("verbose", ParamType.BOOLEAN, False),
    ParamSpec("get_response_schema", ParamType.BOOLEAN, False),
    ParamSpec("doAs", ParamType.STRING, None),
    ParamSpec("reason", ParamType.STRING, ""),
)
_MUTATION = (
    ParamSpec("dryrun", ParamType.BOOLEAN, True),
    ParamSpec("goals", ParamType.CSV_STR, ()),
    ParamSpec("allow_capacity_estimation", ParamType.BOOLEAN, True),
    ParamSpec("excluded_topics", ParamType.STRING, ""),
    ParamSpec("replication_throttle", ParamType.INT, None),
    ParamSpec("stop_ongoing_execution", ParamType.BOOLEAN, False),
    ParamSpec("review_id", ParamType.INT, None),
)

PARAMETERS: dict[EndPoint, tuple[ParamSpec, ...]] = {
    EndPoint.STATE: _COMMON + (
        ParamSpec("substates", ParamType.CSV_STR, ()),
        ParamSpec("super_verbose", ParamType.BOOLEAN, False),
    ),
    EndPoint.LOAD: _COMMON + (
        ParamSpec("allow_capacity_estimation", ParamType.BOOLEAN, True),
        ParamSpec("populate_disk_info", ParamType.BOOLEAN, False),
    ),
    EndPoint.PARTITION_LOAD: _COMMON + (
        ParamSpec("max_load_entries", ParamType.INT, 100),
        ParamSpec("topic", ParamType.STRING, ""),
        ParamSpec("resource", ParamType.STRING, "CPU"),
        ParamSpec("min_valid_partition_ratio", ParamType.STRING, None),
    ),
    EndPoint.PROPOSALS: _COMMON + (
        ParamSpec("ignore_proposal_cache", ParamType.BOOLEAN, False),
        ParamSpec("goals", ParamType.CSV_STR, ()),
        ParamSpec("data_from", ParamType.STRING, "VALID_WINDOWS"),
    ),
    EndPoint.KAFKA_CLUSTER_STATE: _COMMON,
    EndPoint.USER_TASKS: _COMMON + (
        ParamSpec("user_task_ids", ParamType.CSV_STR, ()),
        ParamSpec("types", ParamType.CSV_STR, ()),
        ParamSpec("entries", ParamType.INT, 100),
    ),
    EndPoint.REVIEW_BOARD: _COMMON + (
        ParamSpec("review_ids", ParamType.CSV_INT, ()),
    ),
    EndPoint.PERMISSIONS: _COMMON,
    EndPoint.BOOTSTRAP: _COMMON + (
        ParamSpec("start", ParamType.INT, None),
        ParamSpec("end", ParamType.INT, None),
        ParamSpec("clearmetrics", ParamType.BOOLEAN, True),
    ),
    EndPoint.TRAIN: _COMMON + (
        ParamSpec("start", ParamType.INT, None),
        ParamSpec("end", ParamType.INT, None),
    ),
    # the flight deck (ccx.common.tracing): tracer/recorder/watchdog state,
    # live span stacks + chunk progress, live compile counters; threads=true
    # adds an all-thread stack dump — usable DURING a wedged proposal
    EndPoint.OBSERVABILITY: _COMMON + (
        ParamSpec("threads", ParamType.BOOLEAN, False),
    ),
    EndPoint.REBALANCE: _COMMON + _MUTATION + (
        ParamSpec("rebalance_disk", ParamType.BOOLEAN, False),
        ParamSpec("destination_broker_ids", ParamType.CSV_INT, ()),
        ParamSpec("kafka_assigner", ParamType.BOOLEAN, False),
        ParamSpec("data_from", ParamType.STRING, "VALID_WINDOWS"),
    ),
    EndPoint.ADD_BROKER: _COMMON + _MUTATION + (
        ParamSpec("brokerid", ParamType.CSV_INT, ()),
        ParamSpec("throttle_added_broker", ParamType.BOOLEAN, True),
    ),
    EndPoint.REMOVE_BROKER: _COMMON + _MUTATION + (
        ParamSpec("brokerid", ParamType.CSV_INT, ()),
        ParamSpec("destination_broker_ids", ParamType.CSV_INT, ()),
        ParamSpec("throttle_removed_broker", ParamType.BOOLEAN, True),
    ),
    EndPoint.FIX_OFFLINE_REPLICAS: _COMMON + _MUTATION,
    EndPoint.DEMOTE_BROKER: _COMMON + _MUTATION + (
        ParamSpec("brokerid", ParamType.CSV_INT, ()),
        ParamSpec("skip_urp_demotion", ParamType.BOOLEAN, True),
        ParamSpec("exclude_follower_demotion", ParamType.BOOLEAN, False),
    ),
    EndPoint.STOP_PROPOSAL_EXECUTION: _COMMON + (
        ParamSpec("force_stop", ParamType.BOOLEAN, False),
        ParamSpec("review_id", ParamType.INT, None),
    ),
    EndPoint.PAUSE_SAMPLING: _COMMON + (
        ParamSpec("review_id", ParamType.INT, None),
    ),
    EndPoint.RESUME_SAMPLING: _COMMON + (
        ParamSpec("review_id", ParamType.INT, None),
    ),
    EndPoint.TOPIC_CONFIGURATION: _COMMON + _MUTATION + (
        ParamSpec("topic", ParamType.STRING, ""),
        ParamSpec("replication_factor", ParamType.INT, None),
    ),
    EndPoint.RIGHTSIZE: _COMMON + (
        ParamSpec("num_brokers_to_add", ParamType.INT, -1),
        ParamSpec("partition_count", ParamType.INT, -1),
    ),
    EndPoint.ADMIN: _COMMON + (
        ParamSpec("disable_self_healing_for", ParamType.CSV_STR, ()),
        ParamSpec("enable_self_healing_for", ParamType.CSV_STR, ()),
        ParamSpec("concurrent_partition_movements_per_broker", ParamType.INT, None),
        ParamSpec("concurrent_leader_movements", ParamType.INT, None),
        ParamSpec("review_id", ParamType.INT, None),
    ),
    EndPoint.REVIEW: _COMMON + (
        ParamSpec("approve", ParamType.CSV_INT, ()),
        ParamSpec("discard", ParamType.CSV_INT, ()),
    ),
}


def _coerce(spec: ParamSpec, raw: str) -> Any:
    try:
        if spec.type is ParamType.STRING:
            return raw
        if spec.type is ParamType.BOOLEAN:
            if raw.lower() in ("true", "1", ""):
                return True
            if raw.lower() in ("false", "0"):
                return False
            raise ValueError(raw)
        if spec.type is ParamType.INT:
            return int(raw)
        if spec.type is ParamType.CSV_INT:
            return tuple(int(x) for x in raw.split(",") if x.strip())
        if spec.type is ParamType.CSV_STR:
            return tuple(x.strip() for x in raw.split(",") if x.strip())
    except ValueError:
        raise UserRequestException(
            f"Invalid value {raw!r} for parameter {spec.name}"
        ) from None
    raise UserRequestException(f"Unhandled parameter type {spec.type}")


def parse_params(endpoint: EndPoint, query: dict[str, str]) -> dict[str, Any]:
    """Typed parameter dict; rejects unknown parameters (ref 400)."""
    specs = {s.name: s for s in PARAMETERS[endpoint]}
    out = {name: s.default for name, s in specs.items()}
    for name, raw in query.items():
        spec = specs.get(name)
        if spec is None:
            raise UserRequestException(
                f"Unrecognized parameter {name!r} for endpoint "
                f"{endpoint.value}"
            )
        out[name] = _coerce(spec, raw)
    return out
