"""Operator dashboard.

Parity: the reference serves ``cruise-control-ui`` (a Vue SPA, separate
repo) from its web root (SURVEY.md M5). ccx ships a single-file dashboard —
no build step, stdlib-served — that drives the same REST surface the SPA
uses: cluster summary + per-broker/per-host load (``kafka_cluster_state``,
``load``), monitor windows + executor progress (``state?substates=...``),
partition top-N (``partition_load``), the anomaly-detector / self-healing
panel, the user-task audit trail (``user_tasks``), the review board
(two-step verification), on-demand proposals, and the operator verbs the
SPA exposes (rebalance dryrun/execute, add/remove/demote broker,
fix-offline-replicas, pause/resume sampling, stop execution) — every async
verb long-polled via 202 + User-Task-ID like the SPA's task polling.
"""

PAGE = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8"/>
<title>ccx — cluster dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a22; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
 table { border-collapse: collapse; margin-top: .5rem; }
 td, th { padding: .25rem .7rem; border-bottom: 1px solid #e3e3ea;
          text-align: right; font-variant-numeric: tabular-nums; }
 th { text-align: left; } td:first-child { text-align: left; }
 .bar { display:inline-block; height: .65rem; background:#5b7fff;
        border-radius:2px; vertical-align: middle; }
 .dead { color: #c0392b; font-weight: 600; }
 .ok { color: #1e8e3e; } .muted { color:#777; font-size:.85rem; }
 .warn { color: #b7791f; }
 pre { background:#f6f6f9; padding: .7rem; border-radius:6px;
       max-width: 72rem; overflow-x: auto; }
 button { padding: .35rem .9rem; border-radius: 6px; border: 1px solid #aab;
          background: #eef; cursor: pointer; margin-right:.4rem; }
 button:disabled { opacity:.5 }
 input, select { padding:.25rem .4rem; border:1px solid #aab;
                 border-radius:4px; width: 7rem; }
 .row { margin:.35rem 0; }
 #actionout { margin-top:.5rem; }
</style></head><body>
<h1>ccx — cluster dashboard</h1>
<div class="muted" id="meta">loading…</div>
<h2>Cluster</h2><div id="summary"></div>
<h2>Monitor</h2><div id="monitor"></div>
<h2>Broker load
 <label class="muted"><input type="checkbox" id="byhost"
  style="width:auto" onchange="refresh()"/> group by host</label>
</h2><div id="load"></div>
<h2>Executor</h2><div id="executor"></div>
<h2>Proposals
 <button id="proposebtn" onclick="computeProposals()">Compute proposals</button>
 <button onclick="verb('rebalance', {dryrun: 'true'})">Rebalance (dryrun)</button>
 <button class="dead" onclick="confirm('Execute a real rebalance?') &&
   verb('rebalance', {dryrun: 'false'})">Rebalance (execute)</button>
</h2>
<div id="proposals" class="muted">not computed yet</div>
<h2>Admin actions</h2>
<div class="row">
 broker id(s): <input id="brokerids" placeholder="e.g. 3 or 3,4"/>
 <button onclick="brokerVerb('add_broker')">add</button>
 <button onclick="brokerVerb('remove_broker')">remove</button>
 <button onclick="brokerVerb('demote_broker')">demote</button>
</div>
<div class="row">
 <button onclick="confirm('Execute fix-offline-replicas?') &&
   verb('fix_offline_replicas', {dryrun: 'false'})">fix offline replicas</button>
 <button onclick="verb('pause_sampling', {reason: 'dashboard'})">pause sampling</button>
 <button onclick="verb('resume_sampling', {reason: 'dashboard'})">resume sampling</button>
 <button onclick="verb('stop_proposal_execution', {})">stop execution</button>
</div>
<div id="actionout" class="muted"></div>
<h2>Partition load (top 15)
 <select id="resource" style="width:auto" onchange="refresh()">
  <option>CPU</option><option>NW_IN</option><option>NW_OUT</option>
  <option>DISK</option></select>
</h2><div id="partitions"></div>
<h2>Anomaly detector / self-healing</h2><div id="anomaly"></div>
<h2>Review board</h2><div id="review" class="muted"></div>
<h2>User tasks</h2><div id="tasks"></div>
<h2>Service state</h2><pre id="state"></pre>
<script>
const J = (u) => fetch(u).then(r => r.json());

async function pollTask(resp, url, method) {
  // async verbs return 202 + User-Task-ID; replay the id until COMPLETED.
  // The replay must reuse the original METHOD — operator verbs are
  // POST-only and the server 405s a GET before the task-id branch.
  if (resp.status !== 202) return resp.json();
  const id = resp.headers.get('User-Task-ID');
  for (;;) {
    await new Promise(r => setTimeout(r, 1500));
    const again = await fetch(url, {method: method || 'GET',
                                    headers: {'User-Task-ID': id}});
    if (again.status !== 202) return again.json();
  }
}

async function verb(endpoint, params) {
  const el = document.getElementById('actionout');
  const q = new URLSearchParams(params).toString();
  const url = '/kafkacruisecontrol/' + endpoint + (q ? '?' + q : '');
  el.textContent = endpoint + ' …';
  try {
    const r = await fetch(url, {method: 'POST'});
    const j = await pollTask(r, url, 'POST');
    if (j.RequestInfo && j.RequestInfo.Id !== undefined) {
      el.innerHTML = endpoint + ': parked for two-step review, id <b>' +
        j.RequestInfo.Id + '</b> — approve below, then run';
    } else if (j.errorMessage) {
      el.innerHTML = '<span class="dead">' + endpoint + ': ' +
        j.errorMessage + '</span>';
    } else {
      const s = j.summary || j;
      el.innerHTML = endpoint + ': ok' +
        (s.numReplicaMovements !== undefined ?
         ' — ' + s.numReplicaMovements + ' replica / ' +
         s.numLeadershipMovements + ' leadership movements, verified ' +
         s.verified : '');
    }
  } catch (e) { el.textContent = endpoint + ' error: ' + e; }
  refresh();
}

function brokerVerb(endpoint) {
  const ids = document.getElementById('brokerids').value.trim();
  if (!ids) { alert('enter broker id(s)'); return; }
  const params = {brokerid: ids, dryrun: 'false', reason: 'dashboard'};
  if (confirm(endpoint + ' ' + ids + '?')) verb(endpoint, params);
}

async function review(id, approve) {
  const url = '/kafkacruisecontrol/review?' + new URLSearchParams(
    approve ? {approve: id} : {discard: id});
  await fetch(url, {method: 'POST'});
  refresh();
}

async function computeProposals() {
  const btn = document.getElementById('proposebtn');
  const el = document.getElementById('proposals');
  btn.disabled = true;
  el.textContent = 'computing…';
  try {
    const url = '/kafkacruisecontrol/proposals';
    const r = await fetch(url);
    const j = await pollTask(r, url);
    const s = j.summary || j;
    const goals = (s.goalSummary || []).map(g =>
      `<tr><td>${g.goal}</td><td>${g.hard ? 'hard' : 'soft'}</td>
       <td>${g.violationsBefore}</td><td>${g.violationsAfter}</td>
       <td>${g.costBefore.toFixed(3)}</td><td>${g.costAfter.toFixed(3)}</td></tr>`
    ).join('');
    el.innerHTML =
      `<div>replica movements: <b>${s.numReplicaMovements}</b>,
        leadership movements: <b>${s.numLeadershipMovements}</b>,
        verified: <b class="${s.verified ? 'ok' : 'dead'}">${s.verified}</b>
        ${s.onDemandBalancednessScoreBefore !== undefined ?
          `, balancedness ${s.onDemandBalancednessScoreBefore.toFixed(1)}
           → ${s.onDemandBalancednessScoreAfter.toFixed(1)}` : ''}</div>
       <table><tr><th>Goal</th><th></th><th>viol before</th><th>viol after</th>
       <th>cost before</th><th>cost after</th></tr>${goals}</table>`;
  } catch (e) { el.textContent = 'error: ' + e; }
  btn.disabled = false;
}

function renderMonitor(ms) {
  if (!ms) return '<span class="muted">monitor state unavailable</span>';
  const cls = ms.state === 'RUNNING' || ms.state === 'SAMPLING' ? 'ok' : 'warn';
  return `<table><tr><th>State</th><th>Valid windows</th>
    <th>Valid partitions</th><th>Samples</th><th>Generation</th>
    <th>Trained</th></tr>
    <tr><td class="${cls}">${ms.state}</td><td>${ms.numValidWindows}</td>
    <td>${(100 * ms.validPartitionsRatio).toFixed(1)}%</td>
    <td>${ms.numTotalSamples}</td>
    <td class="muted">${ms.modelGeneration}</td>
    <td>${ms.trained}</td></tr></table>`;
}

function renderExecutor(ex) {
  if (!ex) return '<span class="muted">executor state unavailable</span>';
  let html = `<div class="${ex.state === 'NO_TASK_IN_PROGRESS' ? 'muted' : 'warn'}">
    state: <b>${ex.state}</b></div>`;
  if (ex.taskCounts) {
    const rows = Object.entries(ex.taskCounts).map(([k, v]) =>
      `<tr><td>${k}</td><td>${JSON.stringify(v)}</td></tr>`).join('');
    const pct = ex.totalDataToMoveMb ?
      100 * ex.finishedDataMovementMb / ex.totalDataToMoveMb : 0;
    html += `<div>data moved: ${(ex.finishedDataMovementMb || 0).toFixed(0)} /
      ${(ex.totalDataToMoveMb || 0).toFixed(0)} MB
      <span class="bar" style="width:${1.2 * pct}px"></span></div>
      <table><tr><th>Phase</th><th>Counts</th></tr>${rows}</table>`;
  }
  return html;
}

function renderAnomaly(ad) {
  if (!ad) return '<span class="muted">detector not running</span>';
  const sh = Object.entries(ad.selfHealingEnabled || {}).map(([k, v]) =>
    `<td class="${v ? 'ok' : 'muted'}">${k}: ${v ? 'on' : 'off'}</td>`).join('');
  const recent = (ad.recentAnomalies || []).slice(-8).reverse().map(a => {
    const an = a.anomaly || a;
    return `<tr><td>${an.type || ''}</td>
     <td>${an.description || JSON.stringify(an)}</td>
     <td>${a.action || ''}</td></tr>`;
  }).join('');
  return `<table><tr>${sh}</tr></table>
    <div class="muted">self-healing runs started: ${ad.numSelfHealingStarted},
      pending checks: ${ad.pendingChecks}</div>
    <table><tr><th>Type</th><th>Anomaly</th><th>Action</th></tr>
    ${recent || '<tr><td colspan=3 class="muted">none</td></tr>'}</table>`;
}

function renderTasks(tj) {
  const rows = (tj.userTasks || []).slice(0, 12).map(t =>
    `<tr><td class="muted">${(t.UserTaskId || '').slice(0, 8)}</td>
     <td>${t.Endpoint}</td>
     <td class="${t.Status === 'Completed' ? 'ok' :
                  t.Status === 'CompletedWithError' ? 'dead' : 'warn'}">
       ${t.Status}</td>
     <td>${new Date(t.StartMs).toLocaleTimeString()}</td>
     <td class="muted">${(t.Progress && t.Progress.length) ?
       t.Progress[t.Progress.length - 1].step || '' : ''}</td></tr>`).join('');
  return `<table><tr><th>Task</th><th>Endpoint</th><th>Status</th>
    <th>Started</th><th>Last step</th></tr>
    ${rows || '<tr><td colspan=5 class="muted">none</td></tr>'}</table>`;
}

function renderReview(rb) {
  const rows = (rb.RequestInfo || []).map(r =>
    `<tr><td>${r.Id}</td><td>${r.EndPoint}</td><td>${r.Status}</td>
     <td class="muted">${r.Reason || ''}</td>
     <td>${r.Status === 'PENDING_REVIEW' ?
       `<button onclick="review(${r.Id}, true)">approve</button>
        <button onclick="review(${r.Id}, false)">discard</button>` :
       r.Status === 'APPROVED' ?
       `<button onclick="verb('${r.EndPoint}', {review_id: ${r.Id}, dryrun: 'false'})">run</button>`
       : ''}
     </td></tr>`).join('');
  return rows ?
    `<table><tr><th>Id</th><th>Endpoint</th><th>Status</th><th>Reason</th>
     <th></th></tr>${rows}</table>` :
    'no pending reviews (two-step verification may be disabled)';
}

function renderLoad(ld, byHost) {
  let rows = ld.brokers;
  if (byHost) {
    const hosts = {};
    for (const b of rows) {
      const h = hosts[b.Host] = hosts[b.Host] || {Broker: b.Host, Rack: b.Rack,
        Host: '', BrokerState: 'ALIVE', Replicas: 0, Leaders: 0, CpuPct: 0,
        NwInRate: 0, NwOutRate: 0, DiskMB: 0, n: 0};
      h.n += 1; h.Replicas += b.Replicas; h.Leaders += b.Leaders;
      // percent-of-broker-capacity is not additive — averaged at render
      h.CpuPct += b.CpuPct; h.NwInRate += b.NwInRate;
      h.NwOutRate += b.NwOutRate; h.DiskMB += b.DiskMB;
      if (b.BrokerState !== 'ALIVE') h.BrokerState = b.BrokerState;
    }
    rows = Object.values(hosts);
    for (const h of rows) h.CpuPct /= h.n;
  }
  const maxDisk = Math.max(1, ...rows.map(b => b.DiskMB));
  return '<table><tr><th>' + (byHost ? 'Host' : 'Broker') +
    '</th><th>Rack</th>' + (byHost ? '<th>Brokers</th>' : '<th>Host</th>') +
    '<th>State</th>' +
    '<th>Replicas</th><th>Leaders</th><th>CPU%</th><th>NwIn</th>' +
    '<th>NwOut</th><th>Disk MB</th><th></th></tr>' +
    rows.map(b =>
      `<tr><td>${b.Broker}</td><td>${b.Rack}</td>
       <td>${byHost ? b.n : (b.Host || '')}</td>
       <td class="${b.BrokerState === 'ALIVE' ? 'ok' : 'dead'}">${b.BrokerState}</td>
       <td>${b.Replicas}</td><td>${b.Leaders}</td>
       <td>${b.CpuPct.toFixed(1)}</td><td>${b.NwInRate.toFixed(0)}</td>
       <td>${b.NwOutRate.toFixed(0)}</td><td>${b.DiskMB.toFixed(0)}</td>
       <td><span class="bar" style="width:${120 * b.DiskMB / maxDisk}px"></span></td>
       </tr>`).join('') + '</table>';
}

function renderPartitions(pl) {
  const rows = (pl.records || []).slice(0, 15).map(p =>
    `<tr><td>${p.topic}</td><td>${p.partition}</td><td>${p.leader}</td>
     <td>${(p.cpu ?? 0).toFixed(3)}</td><td>${(p.networkInbound ?? 0).toFixed(1)}</td>
     <td>${(p.networkOutbound ?? 0).toFixed(1)}</td><td>${(p.disk ?? 0).toFixed(1)}</td>
     </tr>`).join('');
  return `<table><tr><th>Topic</th><th>Partition</th><th>Leader</th>
    <th>CPU</th><th>NwIn</th><th>NwOut</th><th>Disk</th></tr>
    ${rows || '<tr><td colspan=7 class="muted">none</td></tr>'}</table>`;
}

async function refresh() {
  try {
    const res = document.getElementById('resource').value;
    const [st, ks, ld, tj, pl, rb] = await Promise.all([
      J('/kafkacruisecontrol/state?substates=monitor,executor,anomaly_detector'),
      J('/kafkacruisecontrol/kafka_cluster_state'),
      J('/kafkacruisecontrol/load'),
      J('/kafkacruisecontrol/user_tasks'),
      J('/kafkacruisecontrol/partition_load?max_load_entries=15&resource=' + res)
        .catch(() => ({})),
      J('/kafkacruisecontrol/review_board').catch(() => ({})),
    ]);
    const s = ks.KafkaBrokerState.Summary;
    document.getElementById('meta').textContent =
      'refreshed ' + new Date().toLocaleTimeString();
    document.getElementById('summary').innerHTML =
      `<table><tr><th>Brokers</th><th>Hosts</th><th>Alive</th><th>Topics</th>
       <th>Partitions</th><th>Replicas</th><th>URP</th></tr>
       <tr><td>${s.Brokers}</td><td>${s.Hosts ?? s.Brokers}</td>
       <td class="${s.AliveBrokers < s.Brokers ?
       'dead' : 'ok'}">${s.AliveBrokers}</td><td>${s.Topics}</td>
       <td>${s.Partitions}</td><td>${s.Replicas}</td>
       <td class="${s.UnderReplicatedPartitions ? 'dead' : 'ok'}">
       ${s.UnderReplicatedPartitions}</td></tr></table>`;
    document.getElementById('monitor').innerHTML =
      renderMonitor(st.MonitorState);
    document.getElementById('load').innerHTML =
      renderLoad(ld, document.getElementById('byhost').checked);
    document.getElementById('executor').innerHTML =
      renderExecutor(st.ExecutorState);
    document.getElementById('partitions').innerHTML = renderPartitions(pl);
    document.getElementById('anomaly').innerHTML =
      renderAnomaly(st.AnomalyDetectorState);
    document.getElementById('review').innerHTML = renderReview(rb);
    document.getElementById('tasks').innerHTML = renderTasks(tj);
    document.getElementById('state').textContent = JSON.stringify(st, null, 2);
  } catch (e) {
    document.getElementById('meta').textContent = 'error: ' + e;
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""
