"""Minimal operator dashboard.

Parity: the reference serves ``cruise-control-ui`` (a Vue SPA, separate
repo) from its web root (SURVEY.md M5). ccx ships a single-file dashboard —
no build step, stdlib-served — that polls the same REST endpoints the UI
uses (``state``, ``load``, ``kafka_cluster_state``) and renders cluster
summary, per-broker load bars, monitor/executor/anomaly state.
"""

PAGE = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8"/>
<title>ccx — cluster dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a22; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
 table { border-collapse: collapse; margin-top: .5rem; }
 td, th { padding: .25rem .7rem; border-bottom: 1px solid #e3e3ea;
          text-align: right; font-variant-numeric: tabular-nums; }
 th { text-align: left; } td:first-child { text-align: left; }
 .bar { display:inline-block; height: .65rem; background:#5b7fff;
        border-radius:2px; vertical-align: middle; }
 .dead { color: #c0392b; font-weight: 600; }
 .ok { color: #1e8e3e; } .muted { color:#777; font-size:.85rem; }
 pre { background:#f6f6f9; padding: .7rem; border-radius:6px;
       max-width: 72rem; overflow-x: auto; }
</style></head><body>
<h1>ccx — cluster dashboard</h1>
<div class="muted" id="meta">loading…</div>
<h2>Cluster</h2><div id="summary"></div>
<h2>Broker load</h2><div id="load"></div>
<h2>Service state</h2><pre id="state"></pre>
<script>
const J = (u) => fetch(u).then(r => r.json());
async function refresh() {
  try {
    const [st, ks, ld] = await Promise.all([
      J('/kafkacruisecontrol/state'),
      J('/kafkacruisecontrol/kafka_cluster_state'),
      J('/kafkacruisecontrol/load'),
    ]);
    const s = ks.KafkaBrokerState.Summary;
    document.getElementById('meta').textContent =
      'refreshed ' + new Date().toLocaleTimeString();
    document.getElementById('summary').innerHTML =
      `<table><tr><th>Brokers</th><th>Alive</th><th>Topics</th>
       <th>Partitions</th><th>Replicas</th><th>URP</th></tr>
       <tr><td>${s.Brokers}</td><td class="${s.AliveBrokers < s.Brokers ?
       'dead' : 'ok'}">${s.AliveBrokers}</td><td>${s.Topics}</td>
       <td>${s.Partitions}</td><td>${s.Replicas}</td>
       <td class="${s.UnderReplicatedPartitions ? 'dead' : 'ok'}">
       ${s.UnderReplicatedPartitions}</td></tr></table>`;
    const maxDisk = Math.max(1, ...ld.brokers.map(b => b.DiskMB));
    document.getElementById('load').innerHTML =
      '<table><tr><th>Broker</th><th>Rack</th><th>State</th>' +
      '<th>Replicas</th><th>Leaders</th><th>CPU%</th><th>NwIn</th>' +
      '<th>NwOut</th><th>Disk MB</th><th></th></tr>' +
      ld.brokers.map(b =>
        `<tr><td>${b.Broker}</td><td>${b.Rack}</td>
         <td class="${b.BrokerState === 'ALIVE' ? 'ok' : 'dead'}">${b.BrokerState}</td>
         <td>${b.Replicas}</td><td>${b.Leaders}</td>
         <td>${b.CpuPct.toFixed(1)}</td><td>${b.NwInRate.toFixed(0)}</td>
         <td>${b.NwOutRate.toFixed(0)}</td><td>${b.DiskMB.toFixed(0)}</td>
         <td><span class="bar" style="width:${120 * b.DiskMB / maxDisk}px"></span></td>
         </tr>`).join('') + '</table>';
    document.getElementById('state').textContent = JSON.stringify(st, null, 2);
  } catch (e) {
    document.getElementById('meta').textContent = 'error: ' + e;
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""
