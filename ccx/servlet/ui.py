"""Operator dashboard.

Parity: the reference serves ``cruise-control-ui`` (a Vue SPA, separate
repo) from its web root (SURVEY.md M5). ccx ships a single-file dashboard —
no build step, stdlib-served — that drives the same REST endpoints the SPA
uses: cluster summary + per-broker load (``kafka_cluster_state``, ``load``),
monitor/executor state (``state``), the anomaly-detector / self-healing
panel (``state?substates=anomaly_detector``), the user-task audit trail
(``user_tasks``), and on-demand proposal computation (``proposals`` with
async 202 + User-Task-ID long-poll, like the SPA's task polling).
"""

PAGE = """<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8"/>
<title>ccx — cluster dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a22; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
 table { border-collapse: collapse; margin-top: .5rem; }
 td, th { padding: .25rem .7rem; border-bottom: 1px solid #e3e3ea;
          text-align: right; font-variant-numeric: tabular-nums; }
 th { text-align: left; } td:first-child { text-align: left; }
 .bar { display:inline-block; height: .65rem; background:#5b7fff;
        border-radius:2px; vertical-align: middle; }
 .dead { color: #c0392b; font-weight: 600; }
 .ok { color: #1e8e3e; } .muted { color:#777; font-size:.85rem; }
 .warn { color: #b7791f; }
 pre { background:#f6f6f9; padding: .7rem; border-radius:6px;
       max-width: 72rem; overflow-x: auto; }
 button { padding: .35rem .9rem; border-radius: 6px; border: 1px solid #aab;
          background: #eef; cursor: pointer; } button:disabled { opacity:.5 }
</style></head><body>
<h1>ccx — cluster dashboard</h1>
<div class="muted" id="meta">loading…</div>
<h2>Cluster</h2><div id="summary"></div>
<h2>Broker load</h2><div id="load"></div>
<h2>Proposals
 <button id="proposebtn" onclick="computeProposals()">Compute proposals</button>
</h2>
<div id="proposals" class="muted">not computed yet</div>
<h2>Anomaly detector / self-healing</h2><div id="anomaly"></div>
<h2>User tasks</h2><div id="tasks"></div>
<h2>Service state</h2><pre id="state"></pre>
<script>
const J = (u) => fetch(u).then(r => r.json());

async function pollTask(resp) {
  // async verbs return 202 + User-Task-ID; replay the id until COMPLETED
  if (resp.status !== 202) return resp.json();
  const id = resp.headers.get('User-Task-ID');
  for (;;) {
    await new Promise(r => setTimeout(r, 1500));
    const again = await fetch('/kafkacruisecontrol/proposals',
                              {headers: {'User-Task-ID': id}});
    if (again.status !== 202) return again.json();
  }
}

async function computeProposals() {
  const btn = document.getElementById('proposebtn');
  const el = document.getElementById('proposals');
  btn.disabled = true;
  el.textContent = 'computing…';
  try {
    const r = await fetch('/kafkacruisecontrol/proposals');
    const j = await pollTask(r);
    const s = j.summary || j;
    const goals = (s.goalSummary || []).map(g =>
      `<tr><td>${g.goal}</td><td>${g.hard ? 'hard' : 'soft'}</td>
       <td>${g.violationsBefore}</td><td>${g.violationsAfter}</td>
       <td>${g.costBefore.toFixed(3)}</td><td>${g.costAfter.toFixed(3)}</td></tr>`
    ).join('');
    el.innerHTML =
      `<div>replica movements: <b>${s.numReplicaMovements}</b>,
        leadership movements: <b>${s.numLeadershipMovements}</b>,
        verified: <b class="${s.verified ? 'ok' : 'dead'}">${s.verified}</b>
        ${s.onDemandBalancednessScoreBefore !== undefined ?
          `, balancedness ${s.onDemandBalancednessScoreBefore.toFixed(1)}
           → ${s.onDemandBalancednessScoreAfter.toFixed(1)}` : ''}</div>
       <table><tr><th>Goal</th><th></th><th>viol before</th><th>viol after</th>
       <th>cost before</th><th>cost after</th></tr>${goals}</table>`;
  } catch (e) { el.textContent = 'error: ' + e; }
  btn.disabled = false;
}

function renderAnomaly(ad) {
  if (!ad) return '<span class="muted">detector not running</span>';
  const sh = Object.entries(ad.selfHealingEnabled || {}).map(([k, v]) =>
    `<td class="${v ? 'ok' : 'muted'}">${k}: ${v ? 'on' : 'off'}</td>`).join('');
  const recent = (ad.recentAnomalies || []).slice(-8).reverse().map(a => {
    const an = a.anomaly || a;
    return `<tr><td>${an.type || ''}</td>
     <td>${an.description || JSON.stringify(an)}</td>
     <td>${a.action || ''}</td></tr>`;
  }).join('');
  return `<table><tr>${sh}</tr></table>
    <div class="muted">self-healing runs started: ${ad.numSelfHealingStarted},
      pending checks: ${ad.pendingChecks}</div>
    <table><tr><th>Type</th><th>Anomaly</th><th>Action</th></tr>
    ${recent || '<tr><td colspan=3 class="muted">none</td></tr>'}</table>`;
}

function renderTasks(tj) {
  const rows = (tj.userTasks || []).slice(0, 12).map(t =>
    `<tr><td class="muted">${(t.UserTaskId || '').slice(0, 8)}</td>
     <td>${t.Endpoint}</td>
     <td class="${t.Status === 'Completed' ? 'ok' :
                  t.Status === 'CompletedWithError' ? 'dead' : 'warn'}">
       ${t.Status}</td>
     <td>${new Date(t.StartMs).toLocaleTimeString()}</td>
     <td class="muted">${(t.Progress && t.Progress.length) ?
       t.Progress[t.Progress.length - 1].step || '' : ''}</td></tr>`).join('');
  return `<table><tr><th>Task</th><th>Endpoint</th><th>Status</th>
    <th>Started</th><th>Last step</th></tr>
    ${rows || '<tr><td colspan=5 class="muted">none</td></tr>'}</table>`;
}

async function refresh() {
  try {
    const [st, ks, ld, tj] = await Promise.all([
      J('/kafkacruisecontrol/state?substates=monitor,executor,anomaly_detector'),
      J('/kafkacruisecontrol/kafka_cluster_state'),
      J('/kafkacruisecontrol/load'),
      J('/kafkacruisecontrol/user_tasks'),
    ]);
    const s = ks.KafkaBrokerState.Summary;
    document.getElementById('meta').textContent =
      'refreshed ' + new Date().toLocaleTimeString();
    document.getElementById('summary').innerHTML =
      `<table><tr><th>Brokers</th><th>Alive</th><th>Topics</th>
       <th>Partitions</th><th>Replicas</th><th>URP</th></tr>
       <tr><td>${s.Brokers}</td><td class="${s.AliveBrokers < s.Brokers ?
       'dead' : 'ok'}">${s.AliveBrokers}</td><td>${s.Topics}</td>
       <td>${s.Partitions}</td><td>${s.Replicas}</td>
       <td class="${s.UnderReplicatedPartitions ? 'dead' : 'ok'}">
       ${s.UnderReplicatedPartitions}</td></tr></table>`;
    const maxDisk = Math.max(1, ...ld.brokers.map(b => b.DiskMB));
    document.getElementById('load').innerHTML =
      '<table><tr><th>Broker</th><th>Rack</th><th>State</th>' +
      '<th>Replicas</th><th>Leaders</th><th>CPU%</th><th>NwIn</th>' +
      '<th>NwOut</th><th>Disk MB</th><th></th></tr>' +
      ld.brokers.map(b =>
        `<tr><td>${b.Broker}</td><td>${b.Rack}</td>
         <td class="${b.BrokerState === 'ALIVE' ? 'ok' : 'dead'}">${b.BrokerState}</td>
         <td>${b.Replicas}</td><td>${b.Leaders}</td>
         <td>${b.CpuPct.toFixed(1)}</td><td>${b.NwInRate.toFixed(0)}</td>
         <td>${b.NwOutRate.toFixed(0)}</td><td>${b.DiskMB.toFixed(0)}</td>
         <td><span class="bar" style="width:${120 * b.DiskMB / maxDisk}px"></span></td>
         </tr>`).join('') + '</table>';
    document.getElementById('anomaly').innerHTML =
      renderAnomaly(st.AnomalyDetectorState);
    document.getElementById('tasks').innerHTML = renderTasks(tj);
    document.getElementById('state').textContent = JSON.stringify(st, null, 2);
  } catch (e) {
    document.getElementById('meta').textContent = 'error: ' + e;
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""
