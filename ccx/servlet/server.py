"""The REST API server.

Parity: ``servlet/KafkaCruiseControlServlet.java`` + ``KafkaCruiseControlApp``
(SURVEY.md C32, L6): endpoints under ``/kafkacruisecontrol/<endpoint>``,
JSON responses, async semantics — a request not finished within
``webserver.request.maxBlockTimeMs`` returns 202 with a ``User-Task-ID``
header and progress body; the client re-requests with that header (or polls
``user_tasks``) until 200. Security (C34) and two-step review purgatory
(C33) wrap dispatch. Built on stdlib ``ThreadingHTTPServer`` — the embedded-
Jetty role with zero extra dependencies.
"""

from __future__ import annotations

import json
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ccx.common.exceptions import UserRequestException
from ccx.detector.anomalies import AnomalyType
from ccx.servlet.endpoints import (
    GET_ENDPOINTS,
    MUTATING_ENDPOINTS,
    POST_ENDPOINTS,
    EndPoint,
    parse_params,
)
from ccx.servlet.purgatory import Purgatory
from ccx.servlet.security import NoopSecurityProvider, authorized
from ccx.service.async_ops import TaskState, UserTaskManager

URL_PREFIX = "/kafkacruisecontrol"


class CruiseControlApp:
    """Server wiring (ref KafkaCruiseControlApp): façade + user tasks +
    purgatory + security behind an HTTP listener."""

    def __init__(self, config, facade, clock=None) -> None:
        self.config = config
        self.facade = facade
        self.user_tasks = UserTaskManager.from_config(config, clock=clock)
        self.purgatory = (
            Purgatory.from_config(config, clock=clock)
            if config["two.step.verification.enabled"]
            else None
        )
        if config["webserver.security.enable"]:
            self.security = config.configured_instance("webserver.security.provider")
        else:
            self.security = NoopSecurityProvider()
        self.max_block_ms = config["webserver.request.maxBlockTimeMs"]
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # ----- lifecycle --------------------------------------------------------

    def start(self) -> tuple[str, int]:
        handler = _make_handler(self)
        addr = (
            self.config["webserver.http.address"],
            self.config["webserver.http.port"],
        )
        self._httpd = ThreadingHTTPServer(addr, handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ccx-rest", daemon=True
        )
        self._thread.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.user_tasks.shutdown()

    # ----- dispatch ---------------------------------------------------------

    def handle(self, method: str, endpoint: EndPoint, params: dict,
               headers: dict, client: str) -> tuple[int, dict, dict]:
        """Returns (status, body, extra_headers)."""
        # --- async replay: a User-Task-ID header resumes a prior request ---
        task_id = headers.get("user-task-id")
        if task_id:
            info = self.user_tasks.get(task_id)
            if info is None:
                raise UserRequestException(f"Unknown User-Task-ID {task_id}")
            # Replay is only valid against the endpoint the task was created
            # for (authorization above was checked against the *requested*
            # endpoint, so an endpoint mismatch would leak another verb's
            # result past the role check — ref UserTaskManager matches the
            # request URL when resuming) and only for the originating client.
            if info.endpoint != endpoint.value.upper():
                raise UserRequestException(
                    f"User-Task-ID {task_id} belongs to endpoint "
                    f"{info.endpoint}, not {endpoint.value.upper()}"
                )
            if info.client_id and client and info.client_id != client:
                raise UserRequestException(
                    f"User-Task-ID {task_id} was created by a different "
                    "client"
                )
            return self._task_response(info)

        # --- two-step review (C33) -----------------------------------------
        if (
            self.purgatory is not None
            and endpoint in MUTATING_ENDPOINTS
            and not params.get("dryrun", True)
        ):
            review_id = params.get("review_id")
            if review_id is None:
                info = self.purgatory.submit(
                    endpoint,
                    {k: v for k, v in params.items() if v is not None},
                    client,
                    reason=params.get("reason", ""),
                )
                return 200, {
                    "RequestInfo": info.to_json(),
                    "message": (
                        "Request parked for review; approve via the review "
                        "endpoint, then re-submit with review_id="
                        f"{info.review_id}"
                    ),
                }, {}
            stored = self.purgatory.take_approved(review_id, endpoint)
            merged = dict(stored.query)
            merged.pop("review_id", None)
            params = {**params, **merged}

        # --- synchronous endpoints -----------------------------------------
        sync = self._sync_dispatch(endpoint, params, headers)
        if sync is not None:
            return 200, sync, {}

        # --- async verbs through the user task manager ---------------------
        fn = self._verb(endpoint, params)
        info = self.user_tasks.submit(
            endpoint.value.upper(), fn,
            request_url=f"{URL_PREFIX}/{endpoint.value}", client_id=client,
            # self-healing fixes bypass the active-task cap (and run at
            # urgent fleet priority below): a saturated dryrun table must
            # never 503 an offline-replica repair
            urgent=endpoint is EndPoint.FIX_OFFLINE_REPLICAS,
        )
        try:
            info.future.result(timeout=self.max_block_ms / 1000.0)
        except TimeoutError:
            pass
        except Exception:
            pass  # surfaced via _task_response
        return self._task_response(info)

    def _task_response(self, info) -> tuple[int, dict, dict]:
        hdrs = {"User-Task-ID": info.task_id}
        if info.state == TaskState.ACTIVE:
            return 202, {
                "progress": info.progress.to_json(),
                "message": "Operation in progress",
                "userTaskId": info.task_id,
            }, hdrs
        if info.state == TaskState.COMPLETED_WITH_ERROR:
            e = info.future.exception()
            status = 400 if isinstance(e, UserRequestException) else 500
            return status, {
                "errorMessage": str(e),
                "stackTrace": "".join(
                    traceback.format_exception(type(e), e, e.__traceback__)
                ),
                "userTaskId": info.task_id,
            }, hdrs
        body = info.future.result()
        if not isinstance(body, dict):
            body = {"result": body}
        body["userTaskId"] = info.task_id
        return 200, body, hdrs

    # ----- endpoint implementations ----------------------------------------

    def _sync_dispatch(self, endpoint: EndPoint, params, headers):
        f = self.facade
        if endpoint is EndPoint.STATE:
            return f.state(params["substates"])
        if endpoint is EndPoint.OBSERVABILITY:
            return f.observability(include_threads=params["threads"])
        if endpoint is EndPoint.KAFKA_CLUSTER_STATE:
            return f.kafka_cluster_state()
        if endpoint is EndPoint.PERMISSIONS:
            auth = self.security.authenticate(headers)
            return {"principal": auth.principal, "roles": sorted(auth.roles)}
        if endpoint is EndPoint.USER_TASKS:
            tasks = self.user_tasks.tasks()
            ids = params["user_task_ids"]
            if ids:
                tasks = [t for t in tasks if t.task_id in ids]
            return {"userTasks": [t.to_json() for t in tasks[: params["entries"]]]}
        if endpoint is EndPoint.REVIEW_BOARD:
            if self.purgatory is None:
                raise UserRequestException(
                    "two.step.verification.enabled is false"
                )
            return {"RequestInfo": self.purgatory.board(params["review_ids"])}
        if endpoint is EndPoint.REVIEW:
            if self.purgatory is None:
                raise UserRequestException(
                    "two.step.verification.enabled is false"
                )
            return {
                "RequestInfo": self.purgatory.review(
                    params["approve"], params["discard"]
                )
            }
        if endpoint is EndPoint.STOP_PROPOSAL_EXECUTION:
            return f.stop_proposal_execution()
        if endpoint is EndPoint.PAUSE_SAMPLING:
            return f.pause_sampling(params["reason"])
        if endpoint is EndPoint.RESUME_SAMPLING:
            return f.resume_sampling(params["reason"])
        if endpoint is EndPoint.ADMIN:
            return self._admin(params)
        return None

    def _admin(self, params) -> dict:
        out = {}
        notifier = self.facade.anomaly_detector.notifier
        toggles = [(n, True) for n in params["enable_self_healing_for"]] + [
            (n, False) for n in params["disable_self_healing_for"]
        ]
        if toggles and not hasattr(notifier, "enabled"):
            raise UserRequestException(
                f"Notifier {type(notifier).__name__} does not support "
                "self-healing toggles"
            )
        for name, value in toggles:
            try:
                anomaly_type = AnomalyType[name.upper()]
            except KeyError:
                raise UserRequestException(
                    f"Unknown anomaly type {name!r}; one of "
                    f"{[t.name.lower() for t in AnomalyType]}"
                ) from None
            notifier.enabled[anomaly_type] = value
            key = "selfHealingEnabled" if value else "selfHealingDisabled"
            out.setdefault(key, []).append(anomaly_type.name)
        cap = params["concurrent_partition_movements_per_broker"]
        if cap is not None:
            self.facade.executor.caps.per_broker_inter = cap
            self.facade.executor.concurrency.cap = cap
            out["concurrentPartitionMovementsPerBroker"] = cap
        leaders = params["concurrent_leader_movements"]
        if leaders is not None:
            self.facade.executor.caps.leadership_batch = leaders
            out["concurrentLeaderMovements"] = leaders
        return out or {"message": "No admin action requested"}

    def _verb(self, endpoint: EndPoint, params):
        f = self.facade
        common = dict(dryrun=params.get("dryrun", True),
                      reason=params.get("reason", ""))

        if endpoint is EndPoint.LOAD:
            return lambda progress: f.load()
        if endpoint is EndPoint.BOOTSTRAP:
            return lambda progress: f.bootstrap(
                params["start"], params["end"],
                clear_metrics=params["clearmetrics"],
            )
        if endpoint is EndPoint.TRAIN:
            return lambda progress: f.train(params["start"], params["end"])
        if endpoint is EndPoint.PARTITION_LOAD:
            return lambda progress: f.partition_load(
                params["max_load_entries"], resource=params["resource"],
                topic=params["topic"],
            )
        if endpoint is EndPoint.PROPOSALS:
            return lambda progress: f.proposals(
                progress, ignore_cache=params["ignore_proposal_cache"]
            )
        if endpoint is EndPoint.RIGHTSIZE:
            return lambda progress: f.rightsize(progress)
        if endpoint is EndPoint.REBALANCE:
            return lambda progress: f.rebalance(
                goals=params["goals"] or None,
                excluded_topics=params["excluded_topics"],
                rebalance_disk=params["rebalance_disk"],
                destination_brokers=params["destination_broker_ids"],
                kafka_assigner=params["kafka_assigner"],
                data_from=params["data_from"],
                replication_throttle=params["replication_throttle"],
                progress=progress, **common,
            )
        if endpoint is EndPoint.ADD_BROKER:
            return lambda progress: f.add_brokers(
                params["brokerid"], goals=params["goals"] or None,
                replication_throttle=params["replication_throttle"],
                progress=progress, **common,
            )
        if endpoint is EndPoint.REMOVE_BROKER:
            return lambda progress: f.remove_brokers(
                params["brokerid"], goals=params["goals"] or None,
                destination_brokers=params["destination_broker_ids"],
                replication_throttle=params["replication_throttle"],
                progress=progress, **common,
            )
        if endpoint is EndPoint.DEMOTE_BROKER:
            return lambda progress: f.demote_brokers(
                params["brokerid"], progress=progress, **common
            )
        if endpoint is EndPoint.FIX_OFFLINE_REPLICAS:
            return lambda progress: f.fix_offline_replicas(
                goals=params["goals"] or None, progress=progress, **common
            )
        if endpoint is EndPoint.TOPIC_CONFIGURATION:
            topic, rf = params["topic"], params["replication_factor"]
            if not topic or rf is None:
                raise UserRequestException(
                    "topic_configuration requires topic and replication_factor"
                )
            return lambda progress: f.update_topic_configuration(
                {topic: rf}, progress=progress, **common
            )
        raise UserRequestException(f"Unhandled endpoint {endpoint.value}")


def _make_handler(app: CruiseControlApp):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet; ops log via logging
            import logging

            logging.getLogger("ccx.servlet.access").debug(
                "%s %s", self.address_string(), fmt % args
            )

        def _dispatch(self, method: str) -> None:
            try:
                # Drain the request body first — with HTTP/1.1 keep-alive an
                # unread body would be parsed as the next request line.
                # Urlencoded form bodies merge into the query parameters
                # (the reference accepts parameters either way).
                body_params: dict[str, str] = {}
                length = int(self.headers.get("Content-Length") or 0)
                if length > 0:
                    raw = self.rfile.read(length)
                    ctype = (self.headers.get("Content-Type") or "").lower()
                    if "application/x-www-form-urlencoded" in ctype:
                        body_params = {
                            k: v[-1]
                            for k, v in urllib.parse.parse_qs(
                                raw.decode(errors="replace"),
                                keep_blank_values=True,
                            ).items()
                        }
                parsed = urllib.parse.urlparse(self.path)
                # non-JSON surfaces: dashboard (ref M5 ui) + Prometheus
                # metrics (ref §5.1 JMX registry -> text exposition).
                # Same authentication gate as the JSON endpoints.
                is_ui = method == "GET" and parsed.path in ("/", "/ui", "/ui/")
                is_metrics = (
                    method == "GET" and parsed.path == URL_PREFIX + "/metrics"
                )
                is_openapi = (
                    method == "GET" and parsed.path == URL_PREFIX + "/openapi"
                )
                if is_ui or is_metrics or is_openapi:
                    hdrs = {k.lower(): v for k, v in self.headers.items()}
                    hdrs["x-ccx-peer-address"] = self.client_address[0]
                    auth = app.security.authenticate(hdrs)
                    if not auth.ok:
                        self._send(
                            401, {"errorMessage": "Authentication required"},
                            {"WWW-Authenticate": auth.challenge or "Basic"},
                        )
                        return
                    if is_ui:
                        from ccx.servlet.ui import PAGE

                        self._send_raw(
                            200, PAGE.encode(), "text/html; charset=utf-8"
                        )
                    elif is_openapi:
                        from ccx.servlet.openapi import openapi_document

                        self._send(200, openapi_document(URL_PREFIX))
                    else:
                        from ccx.common import compilestats
                        from ccx.common.metrics import (
                            PROMETHEUS_CONTENT_TYPE,
                            REGISTRY,
                        )

                        # live compile counters ride every scrape (idempotent
                        # re-registration) — a wedged run's compile activity
                        # is visible from outside the process
                        compilestats.export_gauges(REGISTRY)
                        self._send_raw(
                            200, REGISTRY.render_prometheus().encode(),
                            PROMETHEUS_CONTENT_TYPE,
                        )
                    return
                if not parsed.path.startswith(URL_PREFIX + "/"):
                    self._send(404, {"errorMessage": f"Unknown path {parsed.path}"})
                    return
                name = parsed.path[len(URL_PREFIX) + 1:].strip("/").lower()
                try:
                    endpoint = EndPoint(name)
                except ValueError:
                    self._send(404, {"errorMessage": f"Unknown endpoint {name!r}"})
                    return
                allowed = GET_ENDPOINTS if method == "GET" else POST_ENDPOINTS
                if endpoint not in allowed:
                    self._send(
                        405,
                        {"errorMessage":
                         f"{endpoint.value} does not support {method}"},
                    )
                    return
                headers = {k.lower(): v for k, v in self.headers.items()}
                # Server-injected TCP peer address (cannot be spoofed by the
                # client) — consumed by TrustedProxySecurityProvider.
                headers["x-ccx-peer-address"] = self.client_address[0]
                auth = app.security.authenticate(headers)
                if not auth.ok:
                    self._send(
                        401, {"errorMessage": "Authentication required"},
                        {"WWW-Authenticate": auth.challenge or "Basic"},
                    )
                    return
                if not authorized(auth.roles, endpoint):
                    self._send(
                        403,
                        {"errorMessage":
                         f"{auth.principal} is not authorized for "
                         f"{endpoint.value}"},
                    )
                    return
                query = {
                    k: v[-1]
                    for k, v in urllib.parse.parse_qs(
                        parsed.query, keep_blank_values=True
                    ).items()
                }
                query = {**body_params, **query}
                params = parse_params(endpoint, query)
                status, body, extra = app.handle(
                    method, endpoint, params, headers,
                    client=auth.principal or self.client_address[0],
                )
                self._send(status, body, extra)
            except UserRequestException as e:
                self._send(400, {"errorMessage": str(e)})
            except Exception as e:  # noqa: BLE001 — servlet boundary
                self._send(
                    500,
                    {
                        "errorMessage": str(e),
                        "stackTrace": traceback.format_exc(),
                    },
                )

        def _send(self, status: int, body: dict, extra: dict | None = None) -> None:
            payload = json.dumps({"version": 1, **body}).encode()
            self._send_raw(status, payload, "application/json", extra)

        def _send_raw(self, status: int, payload: bytes, content_type: str,
                      extra: dict | None = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):  # noqa: N802
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

    return Handler
