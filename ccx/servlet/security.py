"""API security — pluggable authentication + role-based authorization.

Parity: ``servlet/security/`` (SURVEY.md C34): a ``SecurityProvider`` SPI
authenticates a request and yields roles; authorization is role-based —
VIEWER (read endpoints), USER (VIEWER + kafka admin reads + user tasks),
ADMIN (everything). Providers: HTTP basic over a credentials file
(``BasicSecurityProvider``), trusted-proxy header auth
(``TrustedProxySecurityProvider``), and a JWT flavor (HMAC-SHA256,
stdlib-only) mirroring ``JwtSecurityProvider``.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json

from ccx.servlet.endpoints import GET_ENDPOINTS, EndPoint

ROLE_VIEWER = "VIEWER"
ROLE_USER = "USER"
ROLE_ADMIN = "ADMIN"
ALL_ROLES = frozenset({ROLE_VIEWER, ROLE_USER, ROLE_ADMIN})

#: minimum role per endpoint class (ref permissions endpoint semantics)
_VIEWER_OK = frozenset(
    {
        EndPoint.STATE, EndPoint.LOAD, EndPoint.PARTITION_LOAD,
        EndPoint.PROPOSALS, EndPoint.KAFKA_CLUSTER_STATE,
        EndPoint.PERMISSIONS,
    }
)
_USER_OK = _VIEWER_OK | {
    EndPoint.USER_TASKS, EndPoint.REVIEW_BOARD,
    # thread stack dumps + file paths: operator-grade, not viewer-grade
    EndPoint.OBSERVABILITY,
}
# everything else (mutating POSTs, admin, review) needs ADMIN


def authorized(roles: set[str], endpoint: EndPoint) -> bool:
    if ROLE_ADMIN in roles:
        return True
    if ROLE_USER in roles:
        return endpoint in _USER_OK
    if ROLE_VIEWER in roles:
        return endpoint in _VIEWER_OK
    return False


class AuthResult:
    def __init__(self, ok: bool, principal: str = "", roles: set[str] | None = None,
                 challenge: str = "") -> None:
        self.ok = ok
        self.principal = principal
        self.roles = roles or set()
        self.challenge = challenge  # WWW-Authenticate header when 401


class SecurityProvider:
    """SPI (ref C34). ``authenticate(headers)`` -> AuthResult."""

    def configure(self, config) -> None:
        pass

    def authenticate(self, headers: dict[str, str]) -> AuthResult:
        raise NotImplementedError


class NoopSecurityProvider(SecurityProvider):
    """Security disabled: everyone is ADMIN (the default when
    ``webserver.security.enable=false``)."""

    def __init__(self, config=None) -> None:
        pass

    def authenticate(self, headers) -> AuthResult:
        return AuthResult(True, "anonymous", {ROLE_ADMIN})


class BasicSecurityProvider(SecurityProvider):
    """HTTP basic auth over a Jetty-style credentials file (ref
    BasicSecurityProvider): lines of ``user: password,ROLE1,ROLE2``."""

    def __init__(self, credentials_file: str | None = None, config=None) -> None:
        self._users: dict[str, tuple[str, set[str]]] = {}
        if credentials_file:
            self._load(credentials_file)
        elif config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        path = config["webserver.auth.credentials.file"]
        if path:
            self._load(path)

    def _load(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                user, _, rest = line.partition(":")
                password, roles = self._split_password_roles(rest.strip())
                self._users[user.strip()] = (password, roles or {ROLE_VIEWER})

    @staticmethod
    def _split_password_roles(rest: str) -> tuple[str, set[str]]:
        """``password,role1,role2`` — the password may contain commas.

        Quoted passwords (Jetty-style ``"pass,word",ADMIN``) are taken
        verbatim; otherwise role names are parsed from the *end* (known role
        tokens only) so a comma inside the password is never silently
        truncated into bogus roles.
        """
        if rest.startswith('"'):
            end = rest.find('"', 1)
            if end > 0:
                password = rest[1:end]
                tail = rest[end + 1 :].lstrip(", ")
                roles = {r.strip().upper() for r in tail.split(",") if r.strip()}
                return password, roles
        parts = [p.strip() for p in rest.split(",")]
        n = len(parts)
        while n > 1 and parts[n - 1].upper() in ALL_ROLES:
            n -= 1
        password = ",".join(parts[:n])
        roles = {p.upper() for p in parts[n:]}
        return password, roles

    def authenticate(self, headers) -> AuthResult:
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("basic "):
            return AuthResult(False, challenge='Basic realm="ccx"')
        try:
            decoded = base64.b64decode(auth.split(None, 1)[1]).decode()
            user, _, password = decoded.partition(":")
        except (binascii.Error, UnicodeDecodeError):
            return AuthResult(False, challenge='Basic realm="ccx"')
        known = self._users.get(user)
        if known is None or not hmac.compare_digest(known[0], password):
            return AuthResult(False, challenge='Basic realm="ccx"')
        return AuthResult(True, user, known[1])


class TrustedProxySecurityProvider(SecurityProvider):
    """Ref TrustedProxySecurityProvider: trust an upstream proxy's
    authenticated-principal header — but only when the TCP peer is one of
    the configured trusted proxies (the server injects the peer address as
    ``CLIENT_ADDRESS_HEADER``); a spoofed header from an untrusted source is
    rejected. Principals in ``admin_principals`` get ADMIN, others USER."""

    HEADER = "x-forwarded-principal"
    CLIENT_ADDRESS_HEADER = "x-ccx-peer-address"  # injected server-side

    def __init__(self, trusted_proxies: tuple[str, ...] = ("127.0.0.1",),
                 admin_principals: tuple[str, ...] = (), config=None) -> None:
        self.trusted_proxies = set(trusted_proxies)
        self.admin_principals = set(admin_principals)
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        ips = config.get("webserver.trusted.proxy.ips")
        if ips:
            self.trusted_proxies = set(ips)
        admins = config.get("webserver.trusted.proxy.admin.principals")
        if admins:
            self.admin_principals = set(admins)

    def authenticate(self, headers) -> AuthResult:
        peer = headers.get(self.CLIENT_ADDRESS_HEADER, "")
        if peer not in self.trusted_proxies:
            return AuthResult(False, challenge="TrustedProxy")
        principal = headers.get(self.HEADER, "")
        if not principal:
            return AuthResult(False, challenge="TrustedProxy")
        roles = (
            {ROLE_ADMIN} if principal in self.admin_principals else {ROLE_USER}
        )
        return AuthResult(True, principal, roles)


class JwtSecurityProvider(SecurityProvider):
    """Ref JwtSecurityProvider, HMAC-SHA256 flavor: ``Authorization: Bearer
    <jwt>`` with claims ``sub`` and ``roles``."""

    def __init__(self, secret: str = "", config=None) -> None:
        self.secret = secret.encode() if secret else b""
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        if not self.secret:
            # The credentials file holds the signing secret's *contents* —
            # never key off the (guessable) path itself.
            path = config["webserver.auth.credentials.file"]
            if path:
                with open(path, "rb") as f:
                    self.secret = f.read().strip()

    @staticmethod
    def _b64url(data: bytes) -> bytes:
        return base64.urlsafe_b64encode(data).rstrip(b"=")

    def issue(self, subject: str, roles: set[str],
              expires_at_s: int | None = None,
              not_before_s: int | None = None) -> str:
        claims: dict = {"sub": subject, "roles": sorted(roles)}
        if expires_at_s is not None:
            claims["exp"] = expires_at_s
        if not_before_s is not None:
            claims["nbf"] = not_before_s
        header = self._b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = self._b64url(json.dumps(claims).encode())
        sig = self._b64url(
            hmac.new(self.secret, header + b"." + payload, hashlib.sha256).digest()
        )
        return (header + b"." + payload + b"." + sig).decode()

    def authenticate(self, headers) -> AuthResult:
        if not self.secret:
            # Fail closed: an unset secret must never verify tokens (an
            # empty HMAC key would accept attacker-signed claims).
            return AuthResult(False, challenge="Bearer")
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("bearer "):
            return AuthResult(False, challenge="Bearer")
        token = auth.split(None, 1)[1]
        try:
            header_b, payload_b, sig_b = token.encode().split(b".")
            expect = self._b64url(
                hmac.new(self.secret, header_b + b"." + payload_b,
                         hashlib.sha256).digest()
            )
            if not hmac.compare_digest(expect, sig_b):
                return AuthResult(False, challenge="Bearer")
            pad = b"=" * (-len(payload_b) % 4)
            claims = json.loads(base64.urlsafe_b64decode(payload_b + pad))
        except (ValueError, binascii.Error):
            return AuthResult(False, challenge="Bearer")
        import time as _time

        now_s = _time.time()
        if "exp" in claims and now_s >= float(claims["exp"]):
            return AuthResult(False, challenge='Bearer error="token expired"')
        if "nbf" in claims and now_s < float(claims["nbf"]):
            return AuthResult(False, challenge='Bearer error="token not yet valid"')
        return AuthResult(
            True, claims.get("sub", ""), set(claims.get("roles", []))
        )


class SpnegoSecurityProvider(SecurityProvider):
    """Kerberos/SPNEGO via GSSAPI (ref SpnegoSecurityProvider, SURVEY.md
    C34): the client sends ``Authorization: Negotiate <base64 token>``; the
    server accepts the GSS security context under its HTTP service
    credential (keytab via standard ``KRB5_KTNAME``) and maps the initiator
    principal to roles.

    The ``gssapi`` package is NOT a hard dependency — construction fails
    with a clear message when it is missing (same import-guard pattern as
    ccx.executor.kafka_admin). Role mapping: principals (sans realm) listed
    in ``webserver.spnego.admin.principals`` get ADMIN, others USER.
    """

    def __init__(self, service_name: str = "HTTP",
                 admin_principals: tuple[str, ...] = (), config=None) -> None:
        try:
            import gssapi
        except ImportError as e:  # pragma: no cover - environment dependent
            raise ImportError(
                "SpnegoSecurityProvider requires the `gssapi` package "
                "(pip install gssapi) and a host Kerberos setup; use "
                "Basic/Jwt/TrustedProxy providers otherwise"
            ) from e
        self._gssapi = gssapi
        self.service_name = service_name
        self.admin_principals = set(admin_principals)
        self._server_creds = None
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        admins = config.get("webserver.spnego.admin.principals")
        if admins:
            self.admin_principals = set(admins)
        svc = config.get("webserver.spnego.service.name")
        if svc:
            self.service_name = svc

    def _creds(self):
        if self._server_creds is None:
            name = self._gssapi.Name(
                f"{self.service_name}@",  # host resolved by the library
                name_type=self._gssapi.NameType.hostbased_service,
            )
            self._server_creds = self._gssapi.Credentials(
                name=name, usage="accept"
            )
        return self._server_creds

    def authenticate(self, headers) -> AuthResult:
        auth = headers.get("authorization", "")
        if not auth.lower().startswith("negotiate "):
            return AuthResult(False, challenge="Negotiate")
        try:
            token = base64.b64decode(auth.split(None, 1)[1])
            ctx = self._gssapi.SecurityContext(creds=self._creds(), usage="accept")
            ctx.step(token)
            if not ctx.complete:
                # multi-round-trip contexts are not supported over stateless
                # HTTP here (ref behavior: single-token SPNEGO)
                return AuthResult(False, challenge="Negotiate")
            principal = str(ctx.initiator_name)
        except Exception:
            return AuthResult(False, challenge="Negotiate")
        short = principal.split("@", 1)[0]
        roles = {ROLE_ADMIN} if (
            principal in self.admin_principals or short in self.admin_principals
        ) else {ROLE_USER, ROLE_VIEWER}
        return AuthResult(True, principal, roles)
