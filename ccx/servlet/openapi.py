"""OpenAPI 3.0 document generated from the endpoint registry.

Parity: the reference's optional Vert.x module (SURVEY.md C36) mirrors the
servlet endpoints behind an OpenAPI contract. This spec is generated from
the single source of truth (``ccx.servlet.endpoints.EndPoint`` +
``PARAMETERS``) and served at ``GET /kafkacruisecontrol/openapi`` — zero
drift risk because there is no second endpoint table to maintain. The
document is also the ROUTE TABLE of the second API surface
(``ccx.servlet.openapi_server.OpenApiServer``, enabled by
``webserver.openapi.port``), which validates every request against this
contract before dispatch — the Vert.x module's contract-first design.
"""

from __future__ import annotations

from ccx import __version__
from ccx.servlet.endpoints import (
    GET_ENDPOINTS,
    PARAMETERS,
    EndPoint,
    ParamType,
)

_TYPE_MAP = {
    ParamType.STRING: {"type": "string"},
    ParamType.BOOLEAN: {"type": "boolean"},
    ParamType.INT: {"type": "integer"},
    ParamType.CSV_INT: {
        "type": "string",
        "description": "comma-separated integers",
    },
    ParamType.CSV_STR: {
        "type": "string",
        "description": "comma-separated strings",
    },
}

_SUMMARY = {
    EndPoint.STATE: "Service state (monitor/executor/analyzer/anomaly detector)",
    EndPoint.LOAD: "Per-broker load + ClusterModelStats block",
    EndPoint.PARTITION_LOAD: "Partitions sorted by resource utilization",
    EndPoint.PROPOSALS: "Current optimization proposals",
    EndPoint.KAFKA_CLUSTER_STATE: "Cluster metadata summary",
    EndPoint.USER_TASKS: "Async task audit trail",
    EndPoint.REVIEW_BOARD: "Two-step verification review board",
    EndPoint.PERMISSIONS: "Caller's roles",
    EndPoint.BOOTSTRAP: "Replay a historical metric range into the monitor",
    EndPoint.TRAIN: "Fit the linear-regression CPU estimation model",
    EndPoint.OBSERVABILITY: (
        "Flight-recorder/tracing state: live span stacks, chunk progress, "
        "compile counters, optional all-thread stack dump"
    ),
    EndPoint.REBALANCE: "Compute (and optionally execute) a rebalance",
    EndPoint.ADD_BROKER: "Move replicas onto new brokers",
    EndPoint.REMOVE_BROKER: "Evacuate brokers before decommissioning",
    EndPoint.FIX_OFFLINE_REPLICAS: "Relocate offline replicas",
    EndPoint.DEMOTE_BROKER: "Move leadership off brokers",
    EndPoint.STOP_PROPOSAL_EXECUTION: "Stop the ongoing execution",
    EndPoint.PAUSE_SAMPLING: "Pause metric sampling",
    EndPoint.RESUME_SAMPLING: "Resume metric sampling",
    EndPoint.TOPIC_CONFIGURATION: "Change topic replication factor",
    EndPoint.RIGHTSIZE: "Provisioner rightsizing",
    EndPoint.ADMIN: "Self-healing toggles + concurrency caps",
    EndPoint.REVIEW: "Approve/discard parked requests",
}


def openapi_document(url_prefix: str = "/kafkacruisecontrol") -> dict:
    paths: dict[str, dict] = {}
    for endpoint in EndPoint:
        method = "get" if endpoint in GET_ENDPOINTS else "post"
        params = [
            {
                "name": spec.name,
                "in": "query",
                "required": False,
                "schema": {
                    **_TYPE_MAP[spec.type],
                    **(
                        {"default": spec.default}
                        if spec.default is not None
                        and not isinstance(spec.default, tuple)
                        else {}
                    ),
                },
            }
            for spec in PARAMETERS[endpoint]
        ]
        paths[f"{url_prefix}/{endpoint.value}"] = {
            method: {
                "summary": _SUMMARY.get(endpoint, endpoint.value),
                "operationId": endpoint.value,
                "parameters": params,
                "responses": {
                    "200": {"description": "JSON response"},
                    "202": {
                        "description": "Async in progress; poll with the "
                        "User-Task-ID response header"
                    },
                    "400": {"description": "Invalid parameter"},
                    "401": {"description": "Authentication required"},
                    "403": {"description": "Role not authorized"},
                },
            }
        }
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "ccx — Cruise Control for TPU",
            "version": __version__,
            "description": (
                "REST surface of the ccx service. Async verbs return 202 "
                "with a User-Task-ID header; replay the request with that "
                "header to poll (see docs/wiki/REST-API.md)."
            ),
        },
        "paths": paths,
    }
