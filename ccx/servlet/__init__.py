"""REST API layer (ref C32-C34: servlet, parameters, security, purgatory)."""
