"""Raw metric records + serde — the cluster-side data plane vocabulary.

Parity: ``cruise-control-metrics-reporter``'s ``metric/{CruiseControlMetric,
BrokerMetric,TopicMetric,PartitionMetric,RawMetricType}.java`` and
``MetricSerde`` (SURVEY.md C37, M3/L0): every broker runs a reporter that
serializes typed raw metrics onto the ``__CruiseControlMetrics`` transport
each ``metric.reporting.interval.ms``; the monitor-side sampler deserializes
and rolls them into samples. The binary format is little-endian and
versioned, record-per-metric, exactly the shape the reference ships.
"""

from __future__ import annotations

import dataclasses
import enum
import struct


class RawMetricType(enum.IntEnum):
    """Representative subset of the reference's ~50 RawMetricTypes, keeping
    the broker/topic/partition scope split (ids are stable wire values)."""

    # broker scope
    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    ALL_TOPIC_REPLICATION_BYTES_IN = 2
    ALL_TOPIC_REPLICATION_BYTES_OUT = 3
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 4
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 5
    ALL_TOPIC_FETCH_REQUEST_RATE = 6
    BROKER_CPU_UTIL = 7
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 8
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 9
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 10
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 11
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 12
    BROKER_LOG_FLUSH_TIME_MS_MAX = 13
    BROKER_LOG_FLUSH_RATE = 14
    BROKER_REQUEST_QUEUE_SIZE = 15
    BROKER_RESPONSE_QUEUE_SIZE = 16
    UNDER_REPLICATED_PARTITIONS = 17
    OFFLINE_LOG_DIRS = 18
    # topic scope
    TOPIC_BYTES_IN = 30
    TOPIC_BYTES_OUT = 31
    TOPIC_REPLICATION_BYTES_IN = 32
    TOPIC_MESSAGES_IN_PER_SEC = 33
    # partition scope
    PARTITION_SIZE = 40
    PARTITION_BYTES_IN = 41
    PARTITION_BYTES_OUT = 42
    PARTITION_MESSAGES_IN = 43

    @property
    def scope(self) -> str:
        if self < 30:
            return "BROKER"
        if self < 40:
            return "TOPIC"
        return "PARTITION"


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    """One raw observation (ref CruiseControlMetric + subclasses: topic and
    partition are empty/-1 outside their scope)."""

    metric_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: str = ""
    partition: int = -1

    @property
    def scope(self) -> str:
        return self.metric_type.scope


_MAGIC = b"CXM"
_VERSION = 1
_HEAD = "<3sBHqqdi H"  # magic, ver, type, time, broker, value, partition, topic-len


def serialize_metric(m: CruiseControlMetric) -> bytes:
    topic_b = m.topic.encode()
    head = struct.pack(
        _HEAD, _MAGIC, _VERSION, int(m.metric_type), m.time_ms, m.broker_id,
        m.value, m.partition, len(topic_b),
    )
    return head + topic_b


def deserialize_metric(buf: bytes) -> CruiseControlMetric:
    magic, version, mtype, t, broker, value, partition, tlen = struct.unpack_from(
        _HEAD, buf
    )
    if magic != _MAGIC:
        raise ValueError(f"bad metric magic {magic!r}")
    if version > _VERSION:
        raise ValueError(f"unsupported metric version {version}")
    off = struct.calcsize(_HEAD)
    topic = buf[off:off + tlen].decode()
    return CruiseControlMetric(
        RawMetricType(mtype), t, broker, value, topic, partition
    )


def serialize_batch(metrics) -> bytes:
    out = bytearray()
    for m in metrics:
        b = serialize_metric(m)
        out += struct.pack("<I", len(b)) + b
    return bytes(out)


def deserialize_batch(buf: bytes) -> list[CruiseControlMetric]:
    out = []
    off = 0
    while off < len(buf):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        out.append(deserialize_metric(buf[off:off + n]))
        off += n
    return out
