"""Metrics transport — the ``__CruiseControlMetrics`` channel.

Parity: in the reference the reporter *produces to a Kafka topic* and the
sampler *consumes* it (SURVEY.md C37/C10, call stack 3.4). The transport SPI
abstracts that channel: an in-memory ring (same-process deployments, tests,
benchmarks) and a file-backed log (cross-process, survives restarts) —
both time-indexed so consumers fetch ``[start_ms, end_ms)`` ranges the way
the sampler consumes topic offsets by timestamp.
"""

from __future__ import annotations

import bisect
import os
import struct
import threading

from ccx.reporter.metrics import (
    CruiseControlMetric,
    deserialize_batch,
    serialize_batch,
)

DEFAULT_CHANNEL = "__CruiseControlMetrics"


class MetricsTransport:
    """SPI: append a batch; read a time range."""

    def produce(self, metrics: list[CruiseControlMetric]) -> None:
        raise NotImplementedError

    def consume(self, start_ms: int, end_ms: int) -> list[CruiseControlMetric]:
        raise NotImplementedError

    def evict_before(self, time_ms: int) -> None:
        pass


class InMemoryTransport(MetricsTransport):
    """Named in-process channels (the embedded-cluster topic analogue).

    ``InMemoryTransport.channel(name)`` returns the process-wide instance so
    a reporter and a sampler wired independently from config meet on the
    same channel, like producer and consumer meeting on a topic name.
    """

    _registry: dict[str, "InMemoryTransport"] = {}
    _registry_lock = threading.Lock()

    def __init__(self) -> None:
        self._times: list[int] = []   # sorted append times
        self._records: list[CruiseControlMetric] = []
        self._lock = threading.Lock()

    @classmethod
    def channel(cls, name: str = DEFAULT_CHANNEL) -> "InMemoryTransport":
        with cls._registry_lock:
            if name not in cls._registry:
                cls._registry[name] = cls()
            return cls._registry[name]

    @classmethod
    def reset(cls, name: str | None = None) -> None:
        with cls._registry_lock:
            if name is None:
                cls._registry.clear()
            else:
                cls._registry.pop(name, None)

    def produce(self, metrics) -> None:
        with self._lock:
            for m in sorted(metrics, key=lambda m: m.time_ms):
                idx = bisect.bisect_right(self._times, m.time_ms)
                self._times.insert(idx, m.time_ms)
                self._records.insert(idx, m)

    def consume(self, start_ms, end_ms) -> list[CruiseControlMetric]:
        with self._lock:
            lo = bisect.bisect_left(self._times, start_ms)
            hi = bisect.bisect_left(self._times, end_ms)
            return list(self._records[lo:hi])

    def evict_before(self, time_ms) -> None:
        with self._lock:
            lo = bisect.bisect_left(self._times, time_ms)
            del self._times[:lo]
            del self._records[:lo]


class FileTransport(MetricsTransport):
    """Append-only metric log under a directory (cross-process channel)."""

    def __init__(self, dir: str, name: str = DEFAULT_CHANNEL) -> None:
        self.dir = dir
        self.path = os.path.join(dir, f"{name}.log")
        self._lock = threading.Lock()
        os.makedirs(dir, exist_ok=True)

    def produce(self, metrics) -> None:
        with self._lock, open(self.path, "ab") as f:
            f.write(serialize_batch(metrics))

    def _read_all(self) -> list[CruiseControlMetric]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            return deserialize_batch(f.read())

    def consume(self, start_ms, end_ms) -> list[CruiseControlMetric]:
        with self._lock:
            return [
                m for m in self._read_all() if start_ms <= m.time_ms < end_ms
            ]

    def evict_before(self, time_ms) -> None:
        with self._lock:
            keep = [m for m in self._read_all() if m.time_ms >= time_ms]
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(serialize_batch(keep))
            os.replace(tmp, self.path)
