"""CruiseControlMetricsReporter — the in-broker reporting agent.

Parity: ``cruise-control-metrics-reporter/.../CruiseControlMetricsReporter
.java`` (SURVEY.md C37, L0, call stack 3.4): runs INSIDE each broker,
samples the broker's Yammer/KafkaMetrics every
``metric.reporting.interval.ms`` and produces serialized raw metrics to the
metrics channel. Here the broker-side metric source is an SPI
(``BrokerMetricsSource``); ``SimulatedBrokerSource`` synthesizes a stable
workload from the simulated cluster's topology (the role the embedded-broker
harness plays in the reference's integration tests).
"""

from __future__ import annotations

import threading

import numpy as np

from ccx.reporter.metrics import CruiseControlMetric, RawMetricType
from ccx.reporter.transport import MetricsTransport


class BrokerMetricsSource:
    """SPI: one broker's raw observations at a point in time."""

    def metrics_for(self, broker_id: int, time_ms: int) -> list[CruiseControlMetric]:
        raise NotImplementedError


class SimulatedBrokerSource(BrokerMetricsSource):
    """Deterministic workload over a SimulatedCluster.

    Each partition gets a stable pseudo-random base load derived from a
    seed; per-broker rollups follow leadership, so killing a broker or
    moving replicas changes the reported stream exactly as it would on a
    real cluster. ``slow_brokers`` injects latency for SlowBrokerFinder
    scenarios.
    """

    def __init__(self, cluster, seed: int = 7) -> None:
        self.cluster = cluster
        self.seed = seed
        self.slow_brokers: dict[int, float] = {}

    def _base(self, tp) -> np.ndarray:
        rng = np.random.default_rng(
            (hash((tp.topic, tp.partition, self.seed))) & 0x7FFFFFFF
        )
        v = rng.random(4)
        # [bytes_in KB/s, bytes_out KB/s, size MB, messages/s]
        return np.array(
            [50 + 400 * v[0], 80 + 600 * v[1], 100 + 900 * v[2], 10 + 90 * v[3]]
        )

    def metrics_for(self, broker_id: int, time_ms: int) -> list[CruiseControlMetric]:
        c = self.cluster
        with c._lock:
            broker = c._brokers.get(broker_id)
            if broker is None or not broker.alive:
                return []
            parts = {tp: p for tp, p in c._partitions.items()}
        out: list[CruiseControlMetric] = []
        bytes_in = bytes_out = repl_in = repl_out = msgs = 0.0
        topic_in: dict[str, float] = {}
        for tp, p in parts.items():
            if broker_id not in p.replicas:
                continue
            base = self._base(tp)
            if p.leader == broker_id:
                out.append(CruiseControlMetric(
                    RawMetricType.PARTITION_BYTES_IN, time_ms, broker_id,
                    base[0], tp.topic, tp.partition,
                ))
                out.append(CruiseControlMetric(
                    RawMetricType.PARTITION_BYTES_OUT, time_ms, broker_id,
                    base[1], tp.topic, tp.partition,
                ))
                out.append(CruiseControlMetric(
                    RawMetricType.PARTITION_MESSAGES_IN, time_ms, broker_id,
                    base[3], tp.topic, tp.partition,
                ))
                bytes_in += base[0]
                bytes_out += base[1]
                msgs += base[3]
                topic_in[tp.topic] = topic_in.get(tp.topic, 0.0) + base[0]
                repl_out += base[0] * (len(p.replicas) - 1)
            else:
                repl_in += base[0]
            # size is reported by every replica holder (ref PARTITION_SIZE)
            out.append(CruiseControlMetric(
                RawMetricType.PARTITION_SIZE, time_ms, broker_id,
                base[2], tp.topic, tp.partition,
            ))
        cpu = min(0.05 + (bytes_in + bytes_out) / 20000.0, 1.0)
        flush = self.slow_brokers.get(broker_id, 5.0)
        broker_rows = {
            RawMetricType.ALL_TOPIC_BYTES_IN: bytes_in,
            RawMetricType.ALL_TOPIC_BYTES_OUT: bytes_out,
            RawMetricType.ALL_TOPIC_REPLICATION_BYTES_IN: repl_in,
            RawMetricType.ALL_TOPIC_REPLICATION_BYTES_OUT: repl_out,
            RawMetricType.ALL_TOPIC_MESSAGES_IN_PER_SEC: msgs,
            RawMetricType.ALL_TOPIC_PRODUCE_REQUEST_RATE: msgs / 10.0,
            RawMetricType.ALL_TOPIC_FETCH_REQUEST_RATE: msgs / 5.0,
            RawMetricType.BROKER_CPU_UTIL: cpu,
            RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: flush,
            RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MAX: 2 * flush,
            RawMetricType.UNDER_REPLICATED_PARTITIONS: 0.0,
            RawMetricType.OFFLINE_LOG_DIRS: float(len(broker.offline_disks)),
        }
        for mtype, value in broker_rows.items():
            out.append(CruiseControlMetric(mtype, time_ms, broker_id, value))
        for topic, v in topic_in.items():
            out.append(CruiseControlMetric(
                RawMetricType.TOPIC_BYTES_IN, time_ms, broker_id, v, topic
            ))
        return out


class MetricsReporter:
    """The per-broker agent (ref CruiseControlMetricsReporter.report())."""

    def __init__(self, source: BrokerMetricsSource, transport: MetricsTransport,
                 broker_id: int, interval_ms: int = 60_000, clock=None) -> None:
        import time as _time

        self.source = source
        self.transport = transport
        self.broker_id = broker_id
        self.interval_ms = interval_ms
        self.clock = clock or (lambda: int(_time.time() * 1000))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def report_once(self, time_ms: int | None = None) -> int:
        t = time_ms if time_ms is not None else self.clock()
        batch = self.source.metrics_for(self.broker_id, t)
        if batch:
            self.transport.produce(batch)
        return len(batch)

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"metrics-reporter-{self.broker_id}",
            daemon=True,
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.report_once()
            except Exception:
                import logging

                logging.getLogger(__name__).exception("metric report failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class ReporterFleet:
    """One reporter per simulated broker (the embedded-cluster harness)."""

    def __init__(self, cluster, transport: MetricsTransport,
                 interval_ms: int = 60_000, clock=None, seed: int = 7) -> None:
        self.source = SimulatedBrokerSource(cluster, seed)
        self.cluster = cluster
        self.reporters = {
            b: MetricsReporter(self.source, transport, b, interval_ms, clock)
            for b in cluster._brokers
        }

    def report_once(self, time_ms: int) -> int:
        return sum(r.report_once(time_ms) for r in self.reporters.values())

    def start(self) -> None:
        for r in self.reporters.values():
            r.start()

    def stop(self) -> None:
        for r in self.reporters.values():
            r.stop()
