"""Execution tasks — the unit of cluster mutation.

Parity: ``executor/{ExecutionProposal,ExecutionTask,ExecutionTaskTracker}
.java`` (SURVEY.md C24): the planner turns each ``ExecutionProposal``
(ccx.proposals) into typed tasks — inter-broker replica movement,
intra-broker (disk) movement, leadership movement — which progress through
the reference's task state machine PENDING → IN_PROGRESS →
{COMPLETED | DEAD | ABORTING → ABORTED}.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools

from ccx.common.metadata import TopicPartition
from ccx.proposals import ExecutionProposal


class TaskType(enum.Enum):
    INTER_BROKER_REPLICA_ACTION = "inter_broker_replica_action"
    INTRA_BROKER_REPLICA_ACTION = "intra_broker_replica_action"
    LEADER_ACTION = "leader_action"


class TaskState(enum.Enum):
    PENDING = "pending"
    IN_PROGRESS = "in_progress"
    ABORTING = "aborting"
    ABORTED = "aborted"
    DEAD = "dead"
    COMPLETED = "completed"


_task_ids = itertools.count()


@dataclasses.dataclass
class ExecutionTask:
    proposal: ExecutionProposal
    type: TaskType
    #: the real TopicPartition (dense indices resolved via the metadata
    #: snapshot the proposals were computed against)
    tp: TopicPartition = None  # type: ignore[assignment]
    task_id: int = dataclasses.field(default_factory=lambda: next(_task_ids))
    state: TaskState = TaskState.PENDING
    start_ms: int = -1
    end_ms: int = -1

    def __post_init__(self) -> None:
        if self.tp is None:
            self.tp = TopicPartition(str(self.proposal.topic), self.proposal.partition)

    @property
    def data_to_move_mb(self) -> float:
        return float(self.proposal.data_to_move)

    @property
    def source_brokers(self) -> tuple[int, ...]:
        """Brokers losing a replica (inter-broker only)."""
        return tuple(
            b for b in self.proposal.old_replicas
            if b not in self.proposal.new_replicas
        )

    @property
    def destination_brokers(self) -> tuple[int, ...]:
        """Brokers gaining a replica (inter-broker only)."""
        return tuple(
            b for b in self.proposal.new_replicas
            if b not in self.proposal.old_replicas
        )

    @property
    def involved_brokers(self) -> tuple[int, ...]:
        return tuple(set(self.source_brokers) | set(self.destination_brokers))

    def transition(self, state: TaskState, now_ms: int = -1) -> None:
        valid = {
            TaskState.PENDING: {TaskState.IN_PROGRESS, TaskState.ABORTED, TaskState.DEAD},
            TaskState.IN_PROGRESS: {
                TaskState.COMPLETED, TaskState.ABORTING, TaskState.DEAD
            },
            TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
        }
        if state not in valid.get(self.state, set()):
            raise ValueError(f"illegal task transition {self.state} -> {state}")
        if state is TaskState.IN_PROGRESS:
            self.start_ms = now_ms
        if state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_ms = now_ms
        self.state = state

    def to_json(self) -> dict:
        return {
            "executionId": self.task_id,
            "type": self.type.value,
            "state": self.state.value.upper(),
            "proposal": self.proposal.to_json(),
        }


def tasks_from_proposals(
    proposals: list[ExecutionProposal],
    metadata=None,
) -> dict[TaskType, list[ExecutionTask]]:
    """Split proposals into typed task lists (ref ExecutionTaskPlanner
    addExecutionProposals): an inter-broker move subsumes its leadership
    change; a pure leadership change becomes a LEADER_ACTION; disk changes on
    surviving brokers become INTRA_BROKER tasks. ``metadata`` (the snapshot
    the proposals were computed against) resolves dense partition indices to
    real TopicPartitions."""
    out: dict[TaskType, list[ExecutionTask]] = {t: [] for t in TaskType}
    for p in proposals:
        tp = None
        if metadata is not None:
            # The optimizer's tensors use dense broker/partition indices;
            # the admin surface speaks real ids — resolve here, where the
            # generation's snapshot is pinned.
            tp = metadata.partitions[p.partition].tp
            ids = [b.broker_id for b in metadata.brokers]
            p = dataclasses.replace(
                p,
                old_replicas=tuple(ids[b] for b in p.old_replicas),
                new_replicas=tuple(ids[b] for b in p.new_replicas),
                old_leader=ids[p.old_leader] if p.old_leader >= 0 else -1,
                new_leader=ids[p.new_leader] if p.new_leader >= 0 else -1,
            )
        inter = set(p.old_replicas) != set(p.new_replicas)
        if inter:
            out[TaskType.INTER_BROKER_REPLICA_ACTION].append(
                ExecutionTask(p, TaskType.INTER_BROKER_REPLICA_ACTION, tp)
            )
        if p.old_leader != p.new_leader:
            # Every leadership change gets a LEADER_ACTION — including those
            # riding an inter-broker move: the reassignment lands the replica,
            # the leadership phase reorders preferred order + elects.
            out[TaskType.LEADER_ACTION].append(
                ExecutionTask(p, TaskType.LEADER_ACTION, tp)
            )
        if p.old_disks and p.new_disks:
            old_disk = dict(zip(p.old_replicas, p.old_disks))
            moved = [
                b for b, d in zip(p.new_replicas, p.new_disks)
                if b in old_disk and old_disk[b] != d
            ]
            if moved:
                out[TaskType.INTRA_BROKER_REPLICA_ACTION].append(
                    ExecutionTask(p, TaskType.INTRA_BROKER_REPLICA_ACTION, tp)
                )
    return out
