"""Replica movement strategies — task ordering policies, chainable.

Parity: ``executor/strategy/`` (SURVEY.md C25): a ``ReplicaMovementStrategy``
decides the order in which pending inter-broker movement tasks are handed to
the cluster; strategies chain (``chainPreviousStrategy``) so e.g. "min-ISR
partitions with offline replicas first, then postpone URPs, then largest
replicas first" composes; ``BaseReplicaMovementStrategy`` (task-id order) is
always the final tie-breaker.

Implementation: each strategy contributes a sort key; a chain sorts by the
key tuple. Cheap, deterministic, and trivially composable — the comparator
semantics of the reference without comparator plumbing.
"""

from __future__ import annotations

from ccx.common.metadata import ClusterMetadata
from ccx.executor.execution_task import ExecutionTask


class ReplicaMovementStrategy:
    """SPI (ref C25). ``key(task, metadata)`` returns a sortable value;
    smaller sorts earlier."""

    def key(self, task: ExecutionTask, metadata: ClusterMetadata | None):
        raise NotImplementedError

    def chain(self, next_strategy: "ReplicaMovementStrategy") -> "ChainedStrategy":
        return ChainedStrategy([self, next_strategy])

    def sorted_tasks(self, tasks: list[ExecutionTask],
                     metadata: ClusterMetadata | None = None) -> list[ExecutionTask]:
        return sorted(tasks, key=lambda t: self.key(t, metadata))

    @property
    def name(self) -> str:
        return type(self).__name__


class ChainedStrategy(ReplicaMovementStrategy):
    def __init__(self, strategies: list[ReplicaMovementStrategy]) -> None:
        self.strategies = []
        for s in strategies:
            if isinstance(s, ChainedStrategy):
                self.strategies.extend(s.strategies)
            else:
                self.strategies.append(s)

    def key(self, task, metadata):
        return tuple(s.key(task, metadata) for s in self.strategies)

    @property
    def name(self) -> str:
        return ",".join(s.name for s in self.strategies)


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Task-id (creation) order — the universal tie-breaker."""

    def __init__(self, config=None) -> None:
        pass

    def key(self, task, metadata):
        return task.task_id


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Largest data first (get the long pole started early)."""

    def __init__(self, config=None) -> None:
        pass

    def key(self, task, metadata):
        return -task.data_to_move_mb


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Smallest data first (maximize early completion count)."""

    def __init__(self, config=None) -> None:
        pass

    def key(self, task, metadata):
        return task.data_to_move_mb


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move healthy partitions before under-replicated ones (an URP move
    adds replication load exactly where the cluster is already fragile)."""

    def __init__(self, config=None) -> None:
        self._cache: tuple[int, frozenset] | None = None

    def _urp_set(self, metadata) -> frozenset:
        # One URP scan per metadata generation, not one per task key —
        # planning rounds sort thousands of tasks against the same snapshot.
        if self._cache is None or self._cache[0] != metadata.generation:
            self._cache = (
                metadata.generation,
                frozenset(p.tp for p in metadata.under_replicated()),
            )
        return self._cache[1]

    def key(self, task, metadata):
        if metadata is None:
            return 0
        return 1 if task.tp in self._urp_set(metadata) else 0


class PrioritizeMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """Partitions at/under min-ISR with offline replicas move first —
    they are one failure away from unavailability (ref C25)."""

    def __init__(self, config=None) -> None:
        pass

    def key(self, task, metadata):
        if metadata is None:
            return 1
        alive = metadata.alive_broker_ids()
        offline = [b for b in task.proposal.old_replicas if b not in alive]
        live = len(task.proposal.old_replicas) - len(offline)
        # at/under min-ISR (approximated as RF-1, the common min.insync.replicas)
        at_risk = offline and live <= max(len(task.proposal.old_replicas) - 1, 1)
        return 0 if at_risk else 1


def build_strategy_chain(config, metadata_unused=None) -> ReplicaMovementStrategy:
    """Instantiate `replica.movement.strategies` + default tie-breaker
    (ref ExecutorConfig / ExecutionTaskPlanner strategy wiring)."""
    from ccx.config.definition import resolve_class

    strategies: list[ReplicaMovementStrategy] = []
    for path in config["replica.movement.strategies"]:
        cls = resolve_class(path) if isinstance(path, str) else path
        strategies.append(cls())
    tail = config["default.replica.movement.strategy.class"]
    tail_cls = resolve_class(tail) if isinstance(tail, str) else tail
    strategies.append(tail_cls())
    return ChainedStrategy(strategies)
