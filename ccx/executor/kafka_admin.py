"""Real-cluster AdminApi over kafka-python (import-guarded).

Parity: the reference's only write path to a live cluster is the Kafka
AdminClient plumbing in ``executor/Executor.java`` / ``KafkaCruiseControlUtils``
(SURVEY.md C28). ``SimulatedAdminClient`` (ccx.executor.admin) serves every
test and benchmark; this module is the production seam — select it with::

    admin.client.class=ccx.executor.kafka_admin.KafkaAdminApi
    bootstrap.servers=host1:9092,host2:9092

kafka-python is NOT a hard dependency: the import is deferred to
construction, with a clear error naming the missing package. Feature gaps in
older kafka-python releases (KIP-455 reassignments, KIP-460 election,
KIP-113 log-dir moves) raise ``UnsupportedAdminOperation`` naming the
required client capability rather than failing obscurely mid-execution.

Conformance: tests/test_admin_conformance.py runs the same AdminApi
behavioral suite against SimulatedAdminClient always, and against this class
when ``CCX_KAFKA_BOOTSTRAP`` points at a reachable broker (skipped
otherwise, like the reference's integration harness without a cluster).
"""

from __future__ import annotations

from ccx.common.metadata import (
    BrokerInfo,
    ClusterMetadata,
    PartitionInfo,
    TopicPartition,
)
from ccx.executor.admin import AdminApi


class UnsupportedAdminOperation(RuntimeError):
    """The installed kafka client lacks an API this operation needs."""


def _require_kafka():
    try:
        import kafka  # noqa: F401
        from kafka import KafkaAdminClient as _K  # noqa: F401
    except ImportError as e:  # pragma: no cover - environment dependent
        raise ImportError(
            "ccx.executor.kafka_admin.KafkaAdminApi requires the "
            "`kafka-python` package (pip install kafka-python); the default "
            "SimulatedAdminClient needs no external dependency"
        ) from e
    return _K


class KafkaAdminApi(AdminApi):
    """AdminApi against a real Kafka cluster via kafka-python."""

    def __init__(self, config=None, bootstrap_servers: str | None = None) -> None:
        K = _require_kafka()
        servers = bootstrap_servers or (
            config["bootstrap.servers"] if config is not None else None
        )
        if not servers:
            raise ValueError("bootstrap.servers is required for KafkaAdminApi")
        self._admin = K(
            bootstrap_servers=servers,
            client_id="ccx-admin",
            request_timeout_ms=(
                config["admin.request.timeout.ms"] if config is not None else 30000
            ),
        )
        self._generation = 0

    # ----- reads ------------------------------------------------------------

    def describe_cluster(self) -> ClusterMetadata:
        from kafka.admin import ConfigResource  # noqa: F401  (import check)

        cluster_info = self._admin.describe_cluster()
        alive_ids = {b["node_id"] for b in cluster_info["brokers"]}
        log_dirs = self._safe_log_dirs()
        brokers = tuple(
            BrokerInfo(
                b["node_id"],
                b.get("rack") or "",
                True,
                max(len(log_dirs.get(b["node_id"], {})), 1),
                tuple(
                    i
                    for i, ok in sorted(log_dirs.get(b["node_id"], {}).items())
                    if not ok
                ),
                # hostname from broker metadata: two brokers on one machine
                # share it, which is what the rack fallback (rack || host)
                # and the model's host axis key on
                host=b.get("host") or "",
            )
            for b in sorted(cluster_info["brokers"], key=lambda b: b["node_id"])
        )

        topics = self._admin.describe_topics()
        parts = []
        for t in sorted(topics, key=lambda t: t["topic"]):
            if t["topic"].startswith("__"):
                continue  # internal topics are not rebalanced (ref behavior)
            for p in sorted(t["partitions"], key=lambda p: p["partition"]):
                tp = TopicPartition(t["topic"], p["partition"])
                parts.append(
                    PartitionInfo(
                        tp,
                        tuple(p["replicas"]),
                        p["leader"] if p["leader"] in alive_ids else -1,
                        tuple(0 for _ in p["replicas"]),
                    )
                )
        self._generation += 1
        return ClusterMetadata(self._generation, brokers, tuple(parts))

    def _safe_log_dirs(self) -> dict[int, dict[int, bool]]:
        try:
            return self.describe_log_dirs()
        except UnsupportedAdminOperation:
            return {}

    def describe_log_dirs(self) -> dict[int, dict[int, bool]]:
        if not hasattr(self._admin, "describe_log_dirs"):
            raise UnsupportedAdminOperation(
                "installed kafka-python lacks describe_log_dirs"
            )
        out: dict[int, dict[int, bool]] = {}
        response = self._admin.describe_log_dirs()
        for broker_id, dirs in _iter_log_dir_response(response):
            out[broker_id] = dirs
        return out

    # ----- writes -----------------------------------------------------------

    def alter_partition_reassignments(self, reassignments) -> None:
        if not hasattr(self._admin, "alter_partition_reassignments"):
            raise UnsupportedAdminOperation(
                "installed kafka-python lacks KIP-455 "
                "alter_partition_reassignments; upgrade to >= 2.2"
            )
        from kafka import TopicPartition as KTP

        self._admin.alter_partition_reassignments(
            {
                KTP(tp.topic, tp.partition): list(target)
                for tp, target in reassignments.items()
            }
        )

    def list_partition_reassignments(self):
        if not hasattr(self._admin, "list_partition_reassignments"):
            raise UnsupportedAdminOperation(
                "installed kafka-python lacks KIP-455 "
                "list_partition_reassignments; upgrade to >= 2.2"
            )
        out = {}
        for ktp, st in self._admin.list_partition_reassignments().items():
            # replicas currently include removing members; the target is
            # replicas - removing + adding, order preserved
            target = [r for r in st["replicas"] if r not in st["removing_replicas"]]
            for a in st["adding_replicas"]:
                if a not in target:
                    target.append(a)
            out[TopicPartition(ktp.topic, ktp.partition)] = tuple(target)
        return out

    def elect_leaders(self, partitions=None) -> None:
        if not hasattr(self._admin, "perform_leader_election"):
            raise UnsupportedAdminOperation(
                "installed kafka-python lacks KIP-460 perform_leader_election"
            )
        from kafka import TopicPartition as KTP
        from kafka.admin import ElectionType

        ktps = (
            None
            if partitions is None
            else [KTP(tp.topic, tp.partition) for tp in partitions]
        )
        self._admin.perform_leader_election(ElectionType.PREFERRED, ktps)

    def alter_replica_log_dirs(self, moves) -> None:
        raise UnsupportedAdminOperation(
            "kafka-python exposes no alterReplicaLogDirs (KIP-113); use the "
            "kafka-reassign-partitions tool for intra-broker moves or a "
            "client with log-dir support"
        )

    def incremental_alter_configs(self, broker_configs) -> None:
        from kafka.admin import ConfigResource, ConfigResourceType

        resources = []
        for broker_id, cfgs in broker_configs.items():
            # kafka-python's alter_configs is the legacy full-replace API;
            # deletions are expressed as empty values
            resources.append(
                ConfigResource(
                    ConfigResourceType.BROKER,
                    str(broker_id),
                    configs={k: ("" if v is None else str(v)) for k, v in cfgs.items()},
                )
            )
        self._admin.alter_configs(resources)

    def describe_configs(self, broker_ids):
        from kafka.admin import ConfigResource, ConfigResourceType

        resources = [
            ConfigResource(ConfigResourceType.BROKER, str(b)) for b in broker_ids
        ]
        responses = self._admin.describe_configs(resources)
        out: dict[int, dict[str, str]] = {b: {} for b in broker_ids}
        for resp in responses:
            for res in resp.resources:
                _ec, _em, _rt, name, entries = res[:5]
                out[int(name)] = {e[0]: e[1] for e in entries if e[1] is not None}
        return out

    def create_topic(self, topic: str, partitions: int, rf: int) -> None:
        from kafka.admin import NewTopic

        self._admin.create_topics(
            [NewTopic(name=topic, num_partitions=partitions, replication_factor=rf)]
        )

    def close(self) -> None:
        self._admin.close()


def _iter_log_dir_response(response):
    """Normalize kafka-python describe_log_dirs responses (shape varies by
    version) into (broker_id, {disk_index: online}) pairs."""
    # v2+: list of (broker_id, response) or a dict
    items = response.items() if hasattr(response, "items") else response
    for entry in items:
        try:
            broker_id, payload = entry
        except (TypeError, ValueError):
            continue
        dirs: dict[int, bool] = {}
        log_dirs = getattr(payload, "log_dirs", None) or []
        for i, d in enumerate(log_dirs):
            error_code = d[0] if isinstance(d, tuple) else getattr(d, "error_code", 0)
            dirs[i] = error_code == 0
        yield int(broker_id), dirs
