"""AdminApi SPI + the in-process simulated cluster.

Parity: the reference's only write path to the managed cluster is the Kafka
AdminClient plumbing in ``executor/Executor.java`` / ``KafkaCruiseControlUtils``
— ``alterPartitionReassignments``, ``electLeaders``, ``alterReplicaLogDirs``,
``describeLogDirs``, ``incrementalAlterConfigs`` + metadata reads (SURVEY.md
C28). ``AdminApi`` is that surface as an SPI; ``SimulatedAdminClient`` backs
it with an in-process cluster that replicates data over (simulated) time —
the role ``CCEmbeddedBroker``/``CCEmbeddedZookeeper`` play in the reference's
integration tests (SURVEY.md §4): multi-broker behavior with no real cluster.

The simulation is deliberately mechanical: an in-flight reassignment copies
``partition_size_mb`` at ``replication_rate_mb_s`` (capped by the throttle)
per adding replica; leadership changes are instant; a dead broker stops
serving and its replicas become offline. That is enough to exercise every
executor state (in-progress/pending/dead tasks, URP handling, progress
polling, concurrency adjustment) the way the reference's tests do.
"""

from __future__ import annotations

import dataclasses
import threading

from ccx.common.metadata import (
    BrokerInfo,
    ClusterMetadata,
    PartitionInfo,
    TopicPartition,
)

THROTTLE_CONFIG = "leader.replication.throttled.rate"


class AdminApi:
    """SPI (ref C28) — everything the framework reads/writes on the cluster."""

    def describe_cluster(self) -> ClusterMetadata:
        raise NotImplementedError

    def alter_partition_reassignments(
        self, reassignments: dict[TopicPartition, tuple[int, ...]]
    ) -> None:
        raise NotImplementedError

    def list_partition_reassignments(self) -> dict[TopicPartition, tuple[int, ...]]:
        """In-flight reassignments: tp -> target replica list."""
        raise NotImplementedError

    def elect_leaders(self, partitions: list[TopicPartition] | None = None) -> None:
        """Preferred leader election (ref electLeaders)."""
        raise NotImplementedError

    def alter_replica_log_dirs(
        self, moves: dict[tuple[TopicPartition, int], int]
    ) -> None:
        """(tp, broker) -> target disk (ref alterReplicaLogDirs)."""
        raise NotImplementedError

    def describe_log_dirs(self) -> dict[int, dict[int, bool]]:
        """broker -> {disk: online} (ref describeLogDirs)."""
        raise NotImplementedError

    def incremental_alter_configs(self, broker_configs: dict[int, dict[str, str]]) -> None:
        raise NotImplementedError

    def describe_configs(self, broker_ids: list[int]) -> dict[int, dict[str, str]]:
        raise NotImplementedError

    def create_topic(self, topic: str, partitions: int, rf: int) -> None:
        raise NotImplementedError


@dataclasses.dataclass
class _SimPartition:
    replicas: list[int]
    leader: int
    dirs: list[int]
    size_mb: float = 100.0
    # in-flight reassignment
    target: list[int] | None = None
    target_dirs: list[int] | None = None
    copied_mb: dict[int, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _SimBroker:
    broker_id: int
    rack: str
    alive: bool = True
    num_disks: int = 1
    offline_disks: set[int] = dataclasses.field(default_factory=set)
    configs: dict[str, str] = dataclasses.field(default_factory=dict)
    host: str = ""  # hostname; brokers sharing it share a physical host


class SimulatedCluster:
    """In-process cluster with time-driven replica movement."""

    def __init__(self, replication_rate_mb_s: float = 1000.0) -> None:
        self.replication_rate_mb_s = replication_rate_mb_s
        self._brokers: dict[int, _SimBroker] = {}
        self._partitions: dict[TopicPartition, _SimPartition] = {}
        self._generation = 0
        self._lock = threading.RLock()
        self.time_ms = 0

    # ----- topology setup ---------------------------------------------------

    def add_broker(self, broker_id: int, rack: str, num_disks: int = 1,
                   host: str = "") -> None:
        with self._lock:
            self._brokers[broker_id] = _SimBroker(
                broker_id, rack, num_disks=num_disks, host=host
            )
            self._generation += 1

    def create_topic(self, topic: str, partitions: int, rf: int,
                     size_mb: float = 100.0) -> None:
        with self._lock:
            alive = sorted(b for b, info in self._brokers.items() if info.alive)
            for p in range(partitions):
                replicas = [alive[(p + i) % len(alive)] for i in range(rf)]
                self._partitions[TopicPartition(topic, p)] = _SimPartition(
                    replicas=replicas, leader=replicas[0],
                    dirs=[0] * rf, size_mb=size_mb,
                )
            self._generation += 1

    # ----- failure injection (ref RandomSelfHealingTest-style fixtures) -----

    def kill_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = False
            for part in self._partitions.values():
                if part.leader == broker_id:
                    live = [b for b in part.replicas
                            if b != broker_id and self._brokers[b].alive]
                    part.leader = live[0] if live else -1
            self._generation += 1

    def restart_broker(self, broker_id: int) -> None:
        with self._lock:
            self._brokers[broker_id].alive = True
            self._generation += 1

    def fail_disk(self, broker_id: int, disk: int) -> None:
        with self._lock:
            self._brokers[broker_id].offline_disks.add(disk)
            self._generation += 1

    # ----- time -------------------------------------------------------------

    def tick(self, ms: int) -> None:
        """Advance simulated time; progress in-flight reassignments."""
        with self._lock:
            self.time_ms += ms
            changed = False
            for tp, part in self._partitions.items():
                if part.target is None:
                    continue
                throttle = self._throttle_mb_s()
                rate = min(self.replication_rate_mb_s, throttle)
                adding = [b for b in part.target if b not in part.replicas]
                for b in adding:
                    if not self._brokers[b].alive:
                        continue
                    part.copied_mb[b] = part.copied_mb.get(b, 0.0) + rate * ms / 1000.0
                if all(part.copied_mb.get(b, 0.0) >= part.size_mb for b in adding):
                    if part.target_dirs is not None:
                        new_dirs = list(part.target_dirs)
                    else:
                        # Preserve disk placement of replicas that stayed;
                        # new replicas land on disk 0.
                        old_dir = dict(zip(part.replicas, part.dirs))
                        new_dirs = [old_dir.get(b, 0) for b in part.target]
                    part.replicas = list(part.target)
                    part.dirs = new_dirs
                    if part.leader not in part.replicas:
                        live = [b for b in part.replicas if self._brokers[b].alive]
                        part.leader = live[0] if live else -1
                    part.target = None
                    part.target_dirs = None
                    part.copied_mb.clear()
                    changed = True
            if changed:
                self._generation += 1

    def _throttle_mb_s(self) -> float:
        for b in self._brokers.values():
            v = b.configs.get(THROTTLE_CONFIG)
            if v is not None:
                return float(v) / 1e6  # bytes/s -> MB/s
        return float("inf")

    # ----- introspection for tests -----------------------------------------

    def partition(self, tp: TopicPartition) -> _SimPartition:
        return self._partitions[tp]

    @property
    def generation(self) -> int:
        return self._generation


class SimulatedAdminClient(AdminApi):
    """AdminApi over a SimulatedCluster (default ``admin.client.class``)."""

    def __init__(self, cluster: SimulatedCluster | None = None, config=None) -> None:
        self.cluster = cluster or SimulatedCluster()

    def describe_cluster(self) -> ClusterMetadata:
        c = self.cluster
        with c._lock:
            brokers = tuple(
                BrokerInfo(b.broker_id, b.rack, b.alive, b.num_disks,
                           tuple(sorted(b.offline_disks)), host=b.host)
                for b in sorted(c._brokers.values(), key=lambda b: b.broker_id)
            )
            parts = tuple(
                PartitionInfo(tp, tuple(p.replicas), p.leader, tuple(p.dirs))
                for tp, p in sorted(c._partitions.items())
            )
            return ClusterMetadata(c._generation, brokers, parts)

    def alter_partition_reassignments(self, reassignments) -> None:
        c = self.cluster
        with c._lock:
            for tp, target in reassignments.items():
                part = c._partitions[tp]
                target = list(target)
                if target == part.replicas:
                    part.target = None
                    continue
                part.target = target
                part.copied_mb = {}
            c._generation += 1

    def list_partition_reassignments(self):
        c = self.cluster
        with c._lock:
            return {
                tp: tuple(p.target)
                for tp, p in c._partitions.items()
                if p.target is not None
            }

    def elect_leaders(self, partitions=None) -> None:
        c = self.cluster
        with c._lock:
            tps = partitions if partitions is not None else list(c._partitions)
            for tp in tps:
                part = c._partitions[tp]
                for b in part.replicas:  # preferred order
                    if c._brokers[b].alive:
                        part.leader = b
                        break
            c._generation += 1

    def alter_replica_log_dirs(self, moves) -> None:
        c = self.cluster
        with c._lock:
            for (tp, broker), disk in moves.items():
                part = c._partitions[tp]
                if broker in part.replicas:
                    part.dirs[part.replicas.index(broker)] = disk
            c._generation += 1

    def describe_log_dirs(self):
        c = self.cluster
        with c._lock:
            return {
                b.broker_id: {d: d not in b.offline_disks
                              for d in range(b.num_disks)}
                for b in c._brokers.values()
            }

    def incremental_alter_configs(self, broker_configs) -> None:
        c = self.cluster
        with c._lock:
            for broker_id, cfgs in broker_configs.items():
                for k, v in cfgs.items():
                    if v is None:
                        c._brokers[broker_id].configs.pop(k, None)
                    else:
                        c._brokers[broker_id].configs[k] = str(v)

    def describe_configs(self, broker_ids):
        c = self.cluster
        with c._lock:
            return {b: dict(c._brokers[b].configs) for b in broker_ids}

    def create_topic(self, topic: str, partitions: int, rf: int) -> None:
        self.cluster.create_topic(topic, partitions, rf)
