"""Executor — applies proposals to the cluster with throttling + polling.

Parity: ``executor/Executor.java`` (SURVEY.md C23, call stack 3.3): the
movement state machine NO_TASK_IN_PROGRESS → STARTING_EXECUTION →
INTER_BROKER_REPLICA_MOVEMENT → (INTRA_BROKER_REPLICA_MOVEMENT) →
LEADER_MOVEMENT → STOPPING_EXECUTION; a single execution reservation; a
progress-polling loop that marks tasks COMPLETED/DEAD; replication throttles
set before and cleared after; concurrency auto-tuned mid-flight
(``ExecutionConcurrencyManager``, C26) from live broker health.

The cluster side is the ``AdminApi`` SPI (ccx.executor.admin): brokers move
the bytes themselves after ``alter_partition_reassignments`` — the executor
only watches ``list_partition_reassignments`` shrink, exactly like the
reference watching AdminClient reassignment state.

Tests drive the loop synchronously with an injected ``waiter`` that advances
the simulated cluster's clock (the role the reference's mocked ``Time``
plays in ``ExecutorTest``).
"""

from __future__ import annotations

import collections
import enum
import threading
import time as _time

import logging

from ccx.common.exceptions import OngoingExecutionException
from ccx.common.metadata import ClusterMetadata
from ccx.common.metrics import REGISTRY
from ccx.executor.admin import THROTTLE_CONFIG, AdminApi
from ccx.executor.execution_task import TaskState, TaskType
from ccx.executor.strategy import build_strategy_chain
from ccx.executor.task_manager import ExecutionCaps, ExecutionTaskManager

LOG = logging.getLogger(__name__)
from ccx.proposals import ExecutionProposal


class ExecutorState(enum.Enum):
    """Ref Executor.ExecutorState.State (C23)."""

    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


class ReplicationThrottleHelper:
    """Ref ``executor/ReplicationThrottleHelper.java`` (C27): set/clear the
    dynamic replication-throttle configs around an execution."""

    def __init__(self, admin: AdminApi, throttle_bytes_per_sec: int) -> None:
        self.admin = admin
        self.rate = throttle_bytes_per_sec

    def set_throttles(self, broker_ids: list[int]) -> None:
        if self.rate is None or self.rate < 0:
            return
        self.admin.incremental_alter_configs(
            {b: {THROTTLE_CONFIG: str(self.rate)} for b in broker_ids}
        )

    def clear_throttles(self, broker_ids: list[int]) -> None:
        if self.rate is None or self.rate < 0:
            return
        self.admin.incremental_alter_configs(
            {b: {THROTTLE_CONFIG: None} for b in broker_ids}
        )


class ExecutionConcurrencyManager:
    """Ref ``executor/ExecutionConcurrencyManager.java`` (C26): raise the
    per-broker movement cap while the cluster is healthy, drop it when
    under-replication or queue pressure appears."""

    def __init__(self, config, broker_metrics_fn=None) -> None:
        self.enabled = config["executor.concurrency.adjuster.enabled"]
        self.cap = config["num.concurrent.partition.movements.per.broker"]
        self.max_cap = config[
            "executor.concurrency.adjuster.max.partition.movements.per.broker"
        ]
        self.min_cap = config[
            "executor.concurrency.adjuster.min.partition.movements.per.broker"
        ]
        #: returns {broker_id: {metric_name: value}} of recent broker health
        self.broker_metrics_fn = broker_metrics_fn
        self.adjustments_up = 0
        self.adjustments_down = 0
        self.last_adjustment = "none"
        REGISTRY.set_gauge(
            "executor.concurrency-cap", self.cap,
            labels={"type": "inter-broker"},
            help="Current per-broker concurrent movement cap "
                 "(auto-tuned by the concurrency adjuster)",
        )

    def adjust(self, metadata: ClusterMetadata) -> int:
        if not self.enabled:
            return self.cap
        unhealthy = bool(metadata.under_replicated()) or bool(
            metadata.dead_broker_ids()
        )
        if not unhealthy and self.broker_metrics_fn is not None:
            metrics = self.broker_metrics_fn() or {}
            for vals in metrics.values():
                if vals.get("UNDER_REPLICATED_PARTITIONS", 0) > 0:
                    unhealthy = True
                    break
        prev = self.cap
        if unhealthy:
            self.cap = max(self.min_cap, self.cap // 2)
        else:
            self.cap = min(self.max_cap, self.cap + 1)
        if self.cap < prev:
            self.adjustments_down += 1
            self.last_adjustment = "down"
            REGISTRY.counter(
                "executor.concurrency-adjust-down",
                "Concurrency-adjuster cap decreases (cluster unhealthy)",
            ).inc()
        elif self.cap > prev:
            self.adjustments_up += 1
            self.last_adjustment = "up"
            REGISTRY.counter(
                "executor.concurrency-adjust-up",
                "Concurrency-adjuster cap increases (cluster healthy)",
            ).inc()
        REGISTRY.set_gauge(
            "executor.concurrency-cap", self.cap,
            labels={"type": "inter-broker"},
            help="Current per-broker concurrent movement cap "
                 "(auto-tuned by the concurrency adjuster)",
        )
        return self.cap

    def observability_json(self) -> dict:
        return {
            "enabled": bool(self.enabled),
            "cap": self.cap,
            "minCap": self.min_cap,
            "maxCap": self.max_cap,
            "adjustmentsUp": self.adjustments_up,
            "adjustmentsDown": self.adjustments_down,
            "lastAdjustment": self.last_adjustment,
        }


class Executor:
    """The L3c layer (ref C23)."""

    def __init__(self, config, admin: AdminApi, clock=None, waiter=None,
                 broker_metrics_fn=None) -> None:
        self.config = config
        self.admin = admin
        self.clock = clock or (lambda: int(_time.time() * 1000))
        #: called between progress polls with the poll interval in ms;
        #: default real sleep, tests advance simulated time instead
        self.waiter = waiter or (lambda ms: _time.sleep(ms / 1000.0))
        self.caps = ExecutionCaps.from_config(config)
        self.strategy = build_strategy_chain(config)
        #: broker_metrics_fn — live broker-health feed (the façade wires the
        #: LoadMonitor's broker aggregator in, ref C26)
        self.concurrency = ExecutionConcurrencyManager(config, broker_metrics_fn)
        self.poll_interval_ms = config["execution.progress.check.interval.ms"]
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = threading.Event()
        self._reservation = threading.Lock()
        self._manager: ExecutionTaskManager | None = None
        self._thread: threading.Thread | None = None
        self._last_uuid: str | None = None
        self._replication_throttle = config["default.replication.throttle"]
        # measured per-wave completion telemetry (ISSUE 20 satellite /
        # ROADMAP round-20 follow-up): real MB/s from finished movement
        # waves, fed back into the fluid wave-pricing model — the
        # facade's re-plans price waves with this instead of the static
        # optimizer.plan.throttle.mbps once a wave has completed
        self._wave_telemetry: collections.deque = collections.deque(maxlen=32)
        self._measured_mbps = 0.0

    # ----- state ------------------------------------------------------------

    @property
    def state(self) -> ExecutorState:
        return self._state

    @property
    def has_ongoing_execution(self) -> bool:
        return self._state is not ExecutorState.NO_TASK_IN_PROGRESS

    def state_json(self) -> dict:
        out = {"state": self._state.value}
        if self._manager is not None:
            out.update(self._manager.tracker.to_json())
            out["triggeredUserTaskId"] = self._last_uuid
        return out

    def observability_json(self) -> dict:
        """The ``executor`` block on GET /observability: live state, the
        concurrency adjuster's auto-tune trail, and whether the current (or
        last) execution is consuming a device-scheduled movement plan."""
        wave_map = (
            self._manager.planner.wave_by_partition
            if self._manager is not None else {}
        )
        return {
            "state": self._state.value,
            "concurrency": self.concurrency.observability_json(),
            "plan": {
                "consuming": bool(wave_map),
                "waves": (max(wave_map.values()) + 1) if wave_map else 0,
                "plannedPartitions": len(wave_map),
                # measured completion telemetry (ISSUE 20 satellite):
                # real per-wave MB/s from finished waves + the EWMA the
                # re-plan pricing consumes (0.0 = nothing measured yet)
                "measuredMbPerSec": round(self._measured_mbps, 3),
                "measuredWaves": list(self._wave_telemetry),
            },
        }

    # ----- entry (ref executeProposals) ------------------------------------

    def execute_proposals(
        self,
        proposals: list[ExecutionProposal],
        metadata: ClusterMetadata,
        uuid: str | None = None,
        replication_throttle: int | None = None,
        background: bool = False,
        plan: object | None = None,
    ) -> ExecutionTaskManager:
        if not self._reservation.acquire(blocking=False):
            raise OngoingExecutionException(
                f"Cannot execute: executor is in state {self._state.value}"
            )
        try:
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            self._last_uuid = uuid
            self._replication_throttle = (
                replication_throttle
                if replication_throttle is not None
                else self.config["default.replication.throttle"]
            )
            self._manager = ExecutionTaskManager(
                proposals, self.strategy, self.caps, metadata, plan=plan
            )
        except BaseException:
            self._state = ExecutorState.NO_TASK_IN_PROGRESS
            self._reservation.release()
            raise
        if background:
            self._thread = threading.Thread(
                target=self._run, name="ProposalExecutionRunnable", daemon=True
            )
            self._thread.start()
        else:
            self._run()
        return self._manager

    def stop_execution(self) -> None:
        """Ref stopProposalExecution: abort pending work, let in-flight
        movements finish (Kafka cannot cancel an in-flight reassignment
        pre-2.4-style; we mirror graceful stop)."""
        if self.has_ongoing_execution:
            self._stop_requested.set()
            self._state = ExecutorState.STOPPING_EXECUTION

    def await_completion(self, timeout_s: float = 30.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # ----- the execution loop (ref ProposalExecutionRunnable) ---------------

    def _run(self) -> None:
        mgr = self._manager
        assert mgr is not None
        throttle = ReplicationThrottleHelper(self.admin, self._replication_throttle)
        brokers = [b.broker_id for b in mgr.metadata.brokers] if mgr.metadata else []
        try:
            # set_throttles inside the try: if the alter-configs RPC itself
            # fails, the finally still resets state + releases the
            # reservation (ref C27 exception-safety around the execute path).
            throttle.set_throttles(brokers)
            self._state = (
                ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
            )
            self._move_replicas(mgr)
            if not self._stop_requested.is_set():
                self._state = (
                    ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
                )
                self._move_disks(mgr)
            if not self._stop_requested.is_set():
                self._state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
                self._move_leadership(mgr)
        finally:
            # Throttles come off on success AND error paths; state and
            # reservation recover even when clear_throttles itself raises.
            try:
                throttle.clear_throttles(brokers)
            finally:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
                self._reservation.release()

    def _abort_pending(self, mgr: ExecutionTaskManager, type_: TaskType) -> None:
        now = self.clock()
        for t in mgr.tracker.tasks_of(type_, TaskState.PENDING):
            t.transition(TaskState.ABORTED, now)

    def _move_replicas(self, mgr: ExecutionTaskManager) -> None:
        type_ = TaskType.INTER_BROKER_REPLICA_ACTION
        # per-wave completion telemetry: group the task set by plan wave
        # (wave 0 = everything when no plan rides the proposal), stamp
        # each wave's first start, and record measured MB/s as waves
        # finish — the feedback the fluid wave-pricing model consumes
        wave_of = {
            id(t): mgr.planner.wave_by_partition.get(
                int(t.proposal.partition), 0
            )
            for t in mgr.tracker.tasks_of(type_)
        }
        wave_started: dict[int, int] = {}
        wave_done: set[int] = set()
        while not mgr.tracker.finished:
            if self._stop_requested.is_set():
                self._abort_pending(mgr, type_)
                break
            metadata = self.admin.describe_cluster()
            cap = self.concurrency.adjust(metadata)
            batch = mgr.planner.inter_broker_batch(mgr.tracker, metadata, cap)
            if batch:
                now = self.clock()
                self.admin.alter_partition_reassignments(
                    {t.tp: tuple(t.proposal.new_replicas) for t in batch}
                )
                for t in batch:
                    t.transition(TaskState.IN_PROGRESS, now)
                    wave_started.setdefault(wave_of[id(t)], now)
            in_progress = mgr.tracker.tasks_of(type_, TaskState.IN_PROGRESS)
            if not in_progress and not mgr.tracker.tasks_of(type_, TaskState.PENDING):
                break
            self.waiter(self.poll_interval_ms)
            self._poll_reassignments(mgr)
            self._settle_waves(mgr, wave_of, wave_started, wave_done)
        self._settle_waves(mgr, wave_of, wave_started, wave_done)

    def _settle_waves(self, mgr: ExecutionTaskManager,
                      wave_of: dict[int, int],
                      wave_started: dict[int, int],
                      wave_done: set[int]) -> None:
        """Record measured MB/s for every started wave whose tasks all
        settled (COMPLETED/DEAD/ABORTED); updates the EWMA rate the
        facade's re-plans consume."""
        terminal = (TaskState.COMPLETED, TaskState.DEAD, TaskState.ABORTED)
        tasks = mgr.tracker.tasks_of(TaskType.INTER_BROKER_REPLICA_ACTION)
        by_wave: dict[int, list] = {}
        for t in tasks:
            by_wave.setdefault(wave_of.get(id(t), 0), []).append(t)
        now = self.clock()
        for w, start in list(wave_started.items()):
            if w in wave_done:
                continue
            ts = by_wave.get(w, [])
            if not ts or not all(t.state in terminal for t in ts):
                continue
            wave_done.add(w)
            moved_mb = sum(
                t.data_to_move_mb for t in ts
                if t.state is TaskState.COMPLETED
            )
            seconds = max((now - start) / 1000.0, 1e-9)
            rate = moved_mb / seconds
            self._wave_telemetry.append({
                "wave": int(w),
                "tasks": len(ts),
                "movedMb": round(float(moved_mb), 3),
                "seconds": round(seconds, 3),
                "mbPerSec": round(rate, 3),
            })
            if moved_mb > 0:
                # EWMA over completed waves: one outlier wave (a stall,
                # an aborted tail) must not whipsaw the re-plan pricing
                self._measured_mbps = (
                    rate if self._measured_mbps <= 0.0
                    else 0.5 * self._measured_mbps + 0.5 * rate
                )
                REGISTRY.set_gauge(
                    "executor-measured-wave-mbps", self._measured_mbps,
                    help="EWMA of measured per-wave inter-broker movement "
                         "rate (MB/s) — the live feedback the movement "
                         "planner prices re-plans with",
                )

    def measured_wave_mb_per_sec(self) -> float:
        """EWMA of measured per-wave movement rate (MB/s); 0.0 until the
        first wave with real data completes."""
        return float(self._measured_mbps)

    def _poll_reassignments(self, mgr: ExecutionTaskManager) -> None:
        in_flight = self.admin.list_partition_reassignments()
        metadata = self.admin.describe_cluster()
        alive = metadata.alive_broker_ids()
        pidx = {p.tp: p for p in metadata.partitions}
        now = self.clock()
        for t in mgr.tracker.tasks_of(
            TaskType.INTER_BROKER_REPLICA_ACTION, TaskState.IN_PROGRESS
        ):
            if t.tp in in_flight:
                # DEAD if every destination broker died mid-flight (ref:
                # tasks whose new replicas are offline are marked dead)
                if t.destination_brokers and all(
                    b not in alive for b in t.destination_brokers
                ):
                    t.transition(TaskState.DEAD, now)
                continue
            current = pidx.get(t.tp)
            if current is not None and set(current.replicas) == set(
                t.proposal.new_replicas
            ):
                t.transition(TaskState.COMPLETED, now)
            else:
                t.transition(TaskState.DEAD, now)

    def _move_disks(self, mgr: ExecutionTaskManager) -> None:
        type_ = TaskType.INTRA_BROKER_REPLICA_ACTION
        while True:
            if self._stop_requested.is_set():
                self._abort_pending(mgr, type_)
                break
            batch = mgr.planner.intra_broker_batch(mgr.tracker)
            if not batch:
                break
            now = self.clock()
            moves: dict[tuple, int] = {}
            for t in batch:
                for b, od, nd in zip(
                    t.proposal.new_replicas, t.proposal.old_disks,
                    t.proposal.new_disks,
                ):
                    if od != nd:
                        moves[(t.tp, b)] = nd
                t.transition(TaskState.IN_PROGRESS, now)
            self.admin.alter_replica_log_dirs(moves)
            # Poll log-dir state until the batch settles (disk moves take
            # real time on real clusters). The alerting threshold only
            # *alerts* (ref: task.execution.alerting.threshold.ms triggers a
            # metric/log, never kills the task — the log-dir move may still
            # complete); DEAD only on real failure signals: partition gone
            # or destination broker dead.
            alert_at = self.clock() + self.config[
                "task.execution.alerting.threshold.ms"
            ]
            alerted: set = set()
            remaining = list(batch)
            while remaining:
                self.waiter(self.poll_interval_ms)
                metadata = self.admin.describe_cluster()
                alive = metadata.alive_broker_ids()
                pidx = {p.tp: p for p in metadata.partitions}
                now = self.clock()
                still = []
                for t in remaining:
                    cur = pidx.get(t.tp)
                    want = {
                        b: nd for b, nd in zip(
                            t.proposal.new_replicas, t.proposal.new_disks
                        )
                    }
                    done = cur is not None and all(
                        want.get(b, d) == d
                        for b, d in zip(cur.replicas, cur.replica_dirs)
                    )
                    broker_dead = any(b not in alive for b in want)
                    if done:
                        t.transition(TaskState.COMPLETED, now)
                    elif cur is None or broker_dead:
                        t.transition(TaskState.DEAD, now)
                    else:
                        if now >= alert_at and t.tp not in alerted:
                            alerted.add(t.tp)
                            REGISTRY.counter("executor.slow-task-alerts").inc()
                            LOG.warning(
                                "intra-broker move %s exceeded alerting "
                                "threshold; still polling", t.tp,
                            )
                        still.append(t)
                remaining = still
                if self._stop_requested.is_set():
                    break

    def _move_leadership(self, mgr: ExecutionTaskManager) -> None:
        type_ = TaskType.LEADER_ACTION
        while True:
            if self._stop_requested.is_set():
                self._abort_pending(mgr, type_)
                break
            batch = mgr.planner.leadership_batch(mgr.tracker)
            if not batch:
                break
            now = self.clock()
            for t in batch:
                t.transition(TaskState.IN_PROGRESS, now)
            # Preferred-leader election elects replicas[0]; first reorder the
            # replica list so the target leader is preferred (a zero-copy
            # reassignment, as the reference's proposals carry the new leader
            # first in the replica list), then elect.
            reorders = {}
            pidx0 = {p.tp: p for p in self.admin.describe_cluster().partitions}
            for t in batch:
                cur = pidx0.get(t.tp)
                if cur is None:
                    continue
                want_leader = t.proposal.new_leader
                if cur.replicas and cur.replicas[0] != want_leader and (
                    want_leader in cur.replicas
                ):
                    reorders[t.tp] = (want_leader,) + tuple(
                        b for b in cur.replicas if b != want_leader
                    )
            if reorders:
                self.admin.alter_partition_reassignments(reorders)
                self.waiter(self.poll_interval_ms)
            self.admin.elect_leaders([t.tp for t in batch])
            metadata = self.admin.describe_cluster()
            pidx = {p.tp: p for p in metadata.partitions}
            now = self.clock()
            for t in batch:
                cur = pidx.get(t.tp)
                if cur is not None and cur.leader == t.proposal.new_leader:
                    t.transition(TaskState.COMPLETED, now)
                else:
                    t.transition(TaskState.DEAD, now)
