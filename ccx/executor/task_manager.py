"""Execution planning — per-broker queues, caps, and progress tracking.

Parity: ``executor/{ExecutionTaskPlanner,ExecutionTaskManager,
ExecutionTaskTracker}.java`` (SURVEY.md C24): proposals become typed task
queues; each planning round hands out the next batch of inter-broker moves
respecting ``num.concurrent.partition.movements.per.broker`` (both source and
destination brokers count), ``max.num.cluster.movements``, and the strategy
chain's ordering; leadership tasks batch under
``num.concurrent.leader.movements``; the tracker aggregates task states for
the ``state?substates=executor`` response.
"""

from __future__ import annotations

import collections
import dataclasses

from ccx.common.metadata import ClusterMetadata
from ccx.executor.execution_task import (
    ExecutionTask,
    TaskState,
    TaskType,
    tasks_from_proposals,
)
from ccx.executor.strategy import ReplicaMovementStrategy
from ccx.proposals import ExecutionProposal


@dataclasses.dataclass
class ExecutionCaps:
    """Ref ExecutorConfig concurrency keys (C24)."""

    per_broker_inter: int = 5
    per_broker_intra: int = 2
    leadership_batch: int = 1000
    max_cluster_movements: int = 1250

    @classmethod
    def from_config(cls, config) -> "ExecutionCaps":
        return cls(
            config["num.concurrent.partition.movements.per.broker"],
            config["num.concurrent.intra.broker.partition.movements"],
            config["num.concurrent.leader.movements"],
            config["max.num.cluster.movements"],
        )


class ExecutionTaskTracker:
    """State/type counts + data-volume progress (ref C24)."""

    def __init__(self, tasks: dict[TaskType, list[ExecutionTask]]) -> None:
        self._tasks = tasks

    def all_tasks(self) -> list[ExecutionTask]:
        return [t for ts in self._tasks.values() for t in ts]

    def tasks_of(self, type_: TaskType,
                 state: TaskState | None = None) -> list[ExecutionTask]:
        ts = self._tasks.get(type_, [])
        return [t for t in ts if state is None or t.state is state]

    def counts(self) -> dict[str, dict[str, int]]:
        out: dict[str, dict[str, int]] = {}
        for type_, ts in self._tasks.items():
            c = collections.Counter(t.state.value for t in ts)
            out[type_.value] = dict(c)
        return out

    @property
    def finished(self) -> bool:
        return all(
            t.state in (TaskState.COMPLETED, TaskState.DEAD, TaskState.ABORTED)
            for t in self.all_tasks()
        )

    def data_moved_mb(self) -> tuple[float, float]:
        inter = self._tasks.get(TaskType.INTER_BROKER_REPLICA_ACTION, [])
        total = sum(t.data_to_move_mb for t in inter)
        done = sum(
            t.data_to_move_mb for t in inter if t.state is TaskState.COMPLETED
        )
        return done, total

    def to_json(self) -> dict:
        done, total = self.data_moved_mb()
        return {
            "taskCounts": self.counts(),
            "finishedDataMovementMb": done,
            "totalDataToMoveMb": total,
        }


class ExecutionTaskPlanner:
    """Hands out ready batches under the caps (ref C24).

    When a device-scheduled movement plan (``ccx.search.movement``) rides the
    proposal, ``wave_by_partition`` maps dense partition index -> wave, and
    ``inter_broker_batch`` serves waves as barriers: while any task of wave W
    is in progress, only waves <= W may start. Per-broker caps and the
    cluster-wide budget remain as defense in depth; with no plan the batching
    is exactly the legacy greedy (test-pinned)."""

    def __init__(self, strategy: ReplicaMovementStrategy,
                 caps: ExecutionCaps,
                 wave_by_partition: dict[int, int] | None = None) -> None:
        self.strategy = strategy
        self.caps = caps
        self.wave_by_partition = wave_by_partition or {}

    def _wave_of(self, task: ExecutionTask) -> int:
        # Partitions absent from the plan (RF changes folded in later, plan
        # truncation) default to wave 0 so they are never starved.
        return self.wave_by_partition.get(int(task.proposal.partition), 0)

    def inter_broker_batch(
        self,
        tracker: ExecutionTaskTracker,
        metadata: ClusterMetadata | None,
        per_broker_cap: int | None = None,
    ) -> list[ExecutionTask]:
        """Next inter-broker tasks to start: strategy order, skipping tasks
        whose source or destination broker is at its concurrent-movement cap,
        bounded by the cluster-wide in-flight cap. With a movement plan, the
        candidate set is first narrowed to the active wave (see class doc)."""
        cap = per_broker_cap if per_broker_cap is not None else self.caps.per_broker_inter
        in_progress = tracker.tasks_of(
            TaskType.INTER_BROKER_REPLICA_ACTION, TaskState.IN_PROGRESS
        )
        in_flight_per_broker: collections.Counter = collections.Counter()
        for t in in_progress:
            for b in t.involved_brokers:
                in_flight_per_broker[b] += 1
        budget = self.caps.max_cluster_movements - len(in_progress)
        batch: list[ExecutionTask] = []
        pending = self.strategy.sorted_tasks(
            tracker.tasks_of(TaskType.INTER_BROKER_REPLICA_ACTION, TaskState.PENDING),
            metadata,
        )
        if self.wave_by_partition and pending:
            if in_progress:
                active = min(self._wave_of(t) for t in in_progress)
            else:
                active = min(self._wave_of(t) for t in pending)
            pending = [t for t in pending if self._wave_of(t) <= active]
        for t in pending:
            if len(batch) >= budget:
                break
            if any(in_flight_per_broker[b] >= cap for b in t.involved_brokers):
                continue
            for b in t.involved_brokers:
                in_flight_per_broker[b] += 1
            batch.append(t)
        return batch

    def intra_broker_batch(self, tracker: ExecutionTaskTracker) -> list[ExecutionTask]:
        in_progress = tracker.tasks_of(
            TaskType.INTRA_BROKER_REPLICA_ACTION, TaskState.IN_PROGRESS
        )
        per_broker: collections.Counter = collections.Counter()
        for t in in_progress:
            for b in t.proposal.new_replicas:
                per_broker[b] += 1
        batch = []
        for t in tracker.tasks_of(
            TaskType.INTRA_BROKER_REPLICA_ACTION, TaskState.PENDING
        ):
            brokers = [
                b for b, od, nd in zip(
                    t.proposal.new_replicas, t.proposal.old_disks,
                    t.proposal.new_disks,
                )
                if od != nd
            ]
            if any(per_broker[b] >= self.caps.per_broker_intra for b in brokers):
                continue
            for b in brokers:
                per_broker[b] += 1
            batch.append(t)
        return batch

    def leadership_batch(self, tracker: ExecutionTaskTracker) -> list[ExecutionTask]:
        pending = tracker.tasks_of(TaskType.LEADER_ACTION, TaskState.PENDING)
        return pending[: self.caps.leadership_batch]


class ExecutionTaskManager:
    """Owns the task lifecycle for one execution (ref C24)."""

    def __init__(
        self,
        proposals: list[ExecutionProposal],
        strategy: ReplicaMovementStrategy,
        caps: ExecutionCaps,
        metadata: ClusterMetadata | None = None,
        plan: object | None = None,
    ) -> None:
        self.metadata = metadata
        self.tasks = tasks_from_proposals(proposals, metadata)
        self.tracker = ExecutionTaskTracker(self.tasks)
        self.planner = ExecutionTaskPlanner(
            strategy, caps, wave_by_partition=_plan_wave_map(plan)
        )

    def mark(self, tasks: list[ExecutionTask], state: TaskState,
             now_ms: int = -1) -> None:
        for t in tasks:
            t.transition(state, now_ms)


def _plan_wave_map(plan: object | None) -> dict[int, int]:
    """dense partition index -> wave, from a ``MovementPlan`` (or any object
    exposing int arrays ``partition``/``wave``). ``None``/empty -> {} (legacy
    greedy batching)."""
    if plan is None:
        return {}
    parts = getattr(plan, "partition", None)
    waves = getattr(plan, "wave", None)
    if parts is None or waves is None:
        return {}
    return {int(p): int(w) for p, w in zip(parts, waves)}
