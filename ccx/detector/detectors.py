"""Scheduled detectors — produce anomalies from monitor/admin state.

Parity: ``detector/{GoalViolationDetector,BrokerFailureDetector,
DiskFailureDetector,MetricAnomalyDetector,TopicAnomalyDetector,
MaintenanceEventDetector}.java`` (SURVEY.md C29, call stack 3.5). Each
detector's ``detect(now_ms)`` returns anomalies for the manager's priority
queue; scheduling lives in the manager so tests can drive detectors
synchronously (the reference mocks its scheduled executor the same way).
"""

from __future__ import annotations

import logging

from ccx.common.exceptions import NotEnoughValidWindowsException
from ccx.detector.anomalies import (
    Anomaly,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    MaintenanceEvent,
    TopicAnomaly,
)
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import evaluate_stack
from ccx.monitor.aggregator import ModelCompletenessRequirements

log = logging.getLogger(__name__)


class GoalViolationDetector:
    """Ref GoalViolationDetector: score ``anomaly.detection.goals`` on the
    current model; violated hard goals (or out-of-band soft goals) raise a
    GoalViolations anomaly. No proposals are kept — the fix recomputes."""

    def __init__(self, load_monitor, config) -> None:
        self.load_monitor = load_monitor
        self.goal_names = tuple(
            g for g in config["anomaly.detection.goals"] if g in GOAL_REGISTRY
        )
        self.goal_config = GoalConfig.from_config(config)

    def detect(self, now_ms: int) -> list[Anomaly]:
        try:
            model, _, _ = self.load_monitor.cluster_model(
                ModelCompletenessRequirements(1, 0.5)
            )
        except NotEnoughValidWindowsException:
            return []
        stack = evaluate_stack(
            model, self.goal_config, ("StructuralFeasibility",) + self.goal_names
        )
        violated = [
            name
            for name, (v, _) in stack.by_name().items()
            if v > 0 and name != "StructuralFeasibility"
        ]
        if not violated:
            return []
        # Fixability heuristic (ref: optimization attempt decides): dead
        # brokers/disks make capacity goals unfixable by rebalance alone.
        return [
            GoalViolations(
                detection_ms=now_ms, fixable_violated_goals=tuple(violated)
            )
        ]


class BrokerFailureDetector:
    """Ref BrokerFailureDetector (AdminClient polling mode): a broker present
    in a previous generation but now dead/absent is failed; first-seen times
    persist across detections (and restarts, via the state file the reference
    keeps in ZK / local file)."""

    def __init__(self, admin, config=None, state_path: str | None = None) -> None:
        self.admin = admin
        if state_path is None and config is not None:
            state_path = config["failed.brokers.file.path"]
            if not state_path:
                import os

                os.makedirs(config["sample.store.dir"], exist_ok=True)
                state_path = os.path.join(
                    config["sample.store.dir"], "failed_brokers.json"
                )
        self.state_path = state_path
        self._known: set[int] = set()
        self._failed_since: dict[int, int] = {}
        if state_path:
            self._load_state()

    def _load_state(self) -> None:
        import json
        import os

        if self.state_path and os.path.exists(self.state_path):
            with open(self.state_path, encoding="utf-8") as f:
                self._failed_since = {
                    int(k): int(v) for k, v in json.load(f).items()
                }

    def _save_state(self) -> None:
        import json
        import os

        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._failed_since, f)
        os.replace(tmp, self.state_path)

    def detect(self, now_ms: int) -> list[Anomaly]:
        metadata = self.admin.describe_cluster()
        alive = metadata.alive_broker_ids()
        present = {b.broker_id for b in metadata.brokers}
        self._known |= present
        dead = (self._known - alive) | metadata.dead_broker_ids()
        for b in dead:
            self._failed_since.setdefault(b, now_ms)
        for b in list(self._failed_since):
            if b in alive:
                del self._failed_since[b]
        self._save_state()
        if not self._failed_since:
            return []
        return [
            BrokerFailures(
                detection_ms=now_ms, failed_brokers=dict(self._failed_since)
            )
        ]


class DiskFailureDetector:
    """Ref DiskFailureDetector: offline log dirs via describeLogDirs."""

    def __init__(self, admin, config=None) -> None:
        self.admin = admin

    def detect(self, now_ms: int) -> list[Anomaly]:
        offline: dict[int, tuple[int, ...]] = {}
        for broker, disks in self.admin.describe_log_dirs().items():
            bad = tuple(d for d, online in disks.items() if not online)
            if bad:
                offline[broker] = bad
        if not offline:
            return []
        return [DiskFailures(detection_ms=now_ms, failed_disks=offline)]


class MetricAnomalyDetector:
    """Ref MetricAnomalyDetector: delegates to the MetricAnomalyFinder SPI
    (default SlowBrokerFinder) over broker metric history."""

    def __init__(self, load_monitor, config) -> None:
        self.finder = config.configured_instance("metric.anomaly.finder.class")
        self.load_monitor = load_monitor

    def detect(self, now_ms: int) -> list[Anomaly]:
        metadata = self.load_monitor.admin.describe_cluster()
        agg = self.load_monitor.broker_aggregator.aggregate(
            len(metadata.brokers)
        )
        return self.finder.find(agg, metadata, now_ms)


class TopicAnomalyDetector:
    """Ref TopicAnomalyDetector + TopicReplicationFactorAnomalyFinder."""

    def __init__(self, admin, config) -> None:
        self.finder = config.configured_instance("topic.anomaly.finder.class")
        self.admin = admin

    def detect(self, now_ms: int) -> list[Anomaly]:
        return self.finder.find(self.admin.describe_cluster(), now_ms)


class MaintenanceEventDetector:
    """Ref MaintenanceEventDetector: drains the MaintenanceEventReader SPI."""

    def __init__(self, config) -> None:
        self.reader = config.configured_instance("maintenance.event.reader.class")

    def detect(self, now_ms: int) -> list[Anomaly]:
        return [
            MaintenanceEvent(
                detection_ms=now_ms,
                event_type=e.get("type", "NO_OP"),
                broker_ids=tuple(e.get("brokers", ())),
            )
            for e in self.reader.read_events(now_ms)
        ]


class TopicReplicationFactorAnomalyFinder:
    """Default `topic.anomaly.finder.class` (ref
    TopicReplicationFactorAnomalyFinder): flags topics whose RF deviates
    from `target.topic.replication.factor`."""

    def __init__(self, config=None) -> None:
        self.target_rf = 0
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        self.target_rf = config["target.topic.replication.factor"]

    def find(self, metadata, now_ms: int) -> list[Anomaly]:
        if self.target_rf <= 0:  # opt-in: no configured target, no anomalies
            return []
        bad: dict[str, int] = {}
        for topic in metadata.topics():
            rfs = {len(p.replicas) for p in metadata.partitions_of(topic)}
            for rf in rfs:
                if rf != self.target_rf:
                    bad[topic] = rf
        if not bad:
            return []
        return [
            TopicAnomaly(detection_ms=now_ms, bad_topics=bad,
                         target_rf=self.target_rf)
        ]


class NoopMaintenanceEventReader:
    """Default `maintenance.event.reader.class`."""

    def __init__(self, config=None) -> None:
        pass

    def read_events(self, now_ms: int) -> list[dict]:
        return []


class QueueMaintenanceEventReader:
    """In-memory event queue (the topic-based reader's role in tests)."""

    def __init__(self, config=None) -> None:
        self.events: list[dict] = []

    def add(self, event: dict) -> None:
        self.events.append(event)

    def read_events(self, now_ms: int) -> list[dict]:
        out, self.events = self.events, []
        return out
