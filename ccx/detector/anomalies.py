"""Anomaly types — the self-healing vocabulary.

Parity: ``detector/`` anomaly classes ``{GoalViolations,BrokerFailures,
DiskFailures,KafkaMetricAnomaly,TopicAnomaly,MaintenanceEvent}.java`` and the
``Anomaly``/``AnomalyType`` SPI roots in cruise-control-core (SURVEY.md C29,
M1). Each anomaly knows how to fix itself through the service façade
(``fix(facade)`` → the reference's ``anomaly.fix()`` dispatching to
removeBrokers / fixOfflineReplicas / rebalance — call stack 3.5).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools


class AnomalyType(enum.IntEnum):
    """Priority order (smaller = more urgent), ref AnomalyType."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    MAINTENANCE_EVENT = 5


_ids = itertools.count()


@dataclasses.dataclass
class Anomaly:
    detection_ms: int
    anomaly_id: str = dataclasses.field(
        default_factory=lambda: f"anomaly-{next(_ids)}"
    )

    @property
    def type(self) -> AnomalyType:
        raise NotImplementedError

    def reason(self) -> str:
        raise NotImplementedError

    def fix(self, facade) -> bool:
        """Apply the self-healing action; returns True if a fix started."""
        raise NotImplementedError

    def __lt__(self, other: "Anomaly") -> bool:  # priority-queue ordering
        return (self.type, self.detection_ms) < (other.type, other.detection_ms)

    def to_json(self) -> dict:
        return {
            "anomalyId": self.anomaly_id,
            "type": self.type.name,
            "detectionMs": self.detection_ms,
            "description": self.reason(),
        }


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """Ref GoalViolations: goals whose hard constraint or balance limit is
    violated on the current model; fixable via a self-healing rebalance."""

    fixable_violated_goals: tuple[str, ...] = ()
    unfixable_violated_goals: tuple[str, ...] = ()

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.GOAL_VIOLATION

    def reason(self) -> str:
        return (
            f"Goal violations: fixable {list(self.fixable_violated_goals)}, "
            f"unfixable {list(self.unfixable_violated_goals)}"
        )

    def fix(self, facade) -> bool:
        if not self.fixable_violated_goals:
            return False
        facade.rebalance(
            dryrun=False,
            reason=f"self-healing: {self.reason()}", self_healing=True
        )
        return True


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """Ref BrokerFailures: dead brokers with first-observed timestamps."""

    failed_brokers: dict[int, int] = dataclasses.field(default_factory=dict)

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.BROKER_FAILURE

    def reason(self) -> str:
        return f"Broker failures detected: {self.failed_brokers}"

    def fix(self, facade) -> bool:
        if not self.failed_brokers:
            return False
        facade.remove_brokers(
            tuple(self.failed_brokers),
            dryrun=False,
            reason=f"self-healing: {self.reason()}", self_healing=True,
        )
        return True


@dataclasses.dataclass
class DiskFailures(Anomaly):
    """Ref DiskFailures: offline log dirs per broker."""

    failed_disks: dict[int, tuple[int, ...]] = dataclasses.field(default_factory=dict)

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.DISK_FAILURE

    def reason(self) -> str:
        return f"Disk failures detected: {self.failed_disks}"

    def fix(self, facade) -> bool:
        if not self.failed_disks:
            return False
        facade.fix_offline_replicas(
            dryrun=False,
            reason=f"self-healing: {self.reason()}", self_healing=True
        )
        return True


@dataclasses.dataclass
class MetricAnomaly(Anomaly):
    """Ref KafkaMetricAnomaly (e.g. a slow broker found by SlowBrokerFinder)."""

    broker_id: int = -1
    metric_name: str = ""
    description: str = ""
    #: suggested remediation: demote (remove leadership) or remove broker
    fix_by_demotion: bool = True

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.METRIC_ANOMALY

    def reason(self) -> str:
        return f"Metric anomaly on broker {self.broker_id}: {self.description}"

    def fix(self, facade) -> bool:
        if self.broker_id < 0:
            return False
        if self.fix_by_demotion:
            facade.demote_brokers(
                (self.broker_id,),
                dryrun=False,
                reason=f"self-healing: {self.reason()}", self_healing=True,
            )
        else:
            facade.remove_brokers(
                (self.broker_id,),
                dryrun=False,
                reason=f"self-healing: {self.reason()}", self_healing=True,
            )
        return True


@dataclasses.dataclass
class TopicAnomaly(Anomaly):
    """Ref TopicAnomaly: topics violating the desired replication factor."""

    bad_topics: dict[str, int] = dataclasses.field(default_factory=dict)
    target_rf: int = 3

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.TOPIC_ANOMALY

    def reason(self) -> str:
        return (
            f"Topics with replication factor != {self.target_rf}: "
            f"{self.bad_topics}"
        )

    def fix(self, facade) -> bool:
        if not self.bad_topics:
            return False
        facade.update_topic_configuration(
            dict.fromkeys(self.bad_topics, self.target_rf),
            dryrun=False,
            reason=f"self-healing: {self.reason()}", self_healing=True,
        )
        return True


@dataclasses.dataclass
class MaintenanceEvent(Anomaly):
    """Ref MaintenanceEvent: operator-scheduled actions read from the
    MaintenanceEventReader SPI."""

    event_type: str = "NO_OP"  # ADD_BROKER/REMOVE_BROKER/DEMOTE_BROKER/REBALANCE/...
    broker_ids: tuple[int, ...] = ()

    @property
    def type(self) -> AnomalyType:
        return AnomalyType.MAINTENANCE_EVENT

    def reason(self) -> str:
        return f"Maintenance event {self.event_type} brokers={list(self.broker_ids)}"

    def fix(self, facade) -> bool:
        reason = f"maintenance: {self.reason()}"
        if self.event_type == "REMOVE_BROKER" and self.broker_ids:
            facade.remove_brokers(self.broker_ids, dryrun=False, reason=reason, self_healing=True)
        elif self.event_type == "ADD_BROKER" and self.broker_ids:
            facade.add_brokers(self.broker_ids, dryrun=False, reason=reason, self_healing=True)
        elif self.event_type == "DEMOTE_BROKER" and self.broker_ids:
            facade.demote_brokers(self.broker_ids, dryrun=False, reason=reason, self_healing=True)
        elif self.event_type == "REBALANCE":
            facade.rebalance(dryrun=False, reason=reason, self_healing=True)
        else:
            return False
        return True
