"""AnomalyDetectorManager — scheduling, priority queue, self-healing.

Parity: ``detector/AnomalyDetectorManager.java`` (SURVEY.md C29, call stack
3.5): per-type detection intervals feed a priority queue consumed by the
manager, which asks the ``AnomalyNotifier`` what to do — IGNORE, CHECK
(requeue after a delay), or FIX (invoke the anomaly's self-healing action
through the façade). The manager records anomaly history and self-healing
state for the ``state?substates=anomaly_detector`` response.

Tests (and the façade's synchronous paths) call ``run_once``; production
runs the background thread via ``start_detection``.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time as _time

from ccx.detector.anomalies import Anomaly, AnomalyType
from ccx.detector.detectors import (
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MaintenanceEventDetector,
    MetricAnomalyDetector,
    TopicAnomalyDetector,
)
from ccx.detector.notifier import Action

log = logging.getLogger(__name__)

HISTORY_LIMIT = 100

#: stream-detector cluster label for the service's own periodic poll
#: rounds — the one live cluster this Cruise Control instance watches
POLL_CLUSTER = "live"


class AnomalyDetectorManager:
    def __init__(self, config, load_monitor, facade=None, clock=None) -> None:
        self.config = config
        self.load_monitor = load_monitor
        self.facade = facade  # set later by the service wiring if needed
        self.clock = clock or (lambda: int(_time.time() * 1000))
        self.notifier = config.configured_instance("anomaly.notifier.class")
        admin = load_monitor.admin
        self.detectors = {
            AnomalyType.GOAL_VIOLATION: GoalViolationDetector(load_monitor, config),
            AnomalyType.BROKER_FAILURE: BrokerFailureDetector(admin, config),
            AnomalyType.DISK_FAILURE: DiskFailureDetector(admin, config),
            AnomalyType.METRIC_ANOMALY: MetricAnomalyDetector(load_monitor, config),
            AnomalyType.TOPIC_ANOMALY: TopicAnomalyDetector(admin, config),
            AnomalyType.MAINTENANCE_EVENT: MaintenanceEventDetector(config),
        }
        self._queue: list[tuple[int, Anomaly]] = []  # (ready_ms, anomaly)
        self._lock = threading.RLock()
        self._drain_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.history: list[dict] = []
        self.metrics = {t: 0 for t in AnomalyType}
        self.num_self_healing_started = 0
        # the live-stream detector (ISSUE 20): subscribes to the signals
        # already flowing (window outcomes, warm-pressure bands, devmem
        # verdicts) and fires the SAME facade verbs the queue path does,
        # at urgent priority (self_healing=True), one verb per episode
        from ccx.detector.stream import StreamDetector

        self.stream = StreamDetector(
            config, healer=self._stream_heal, clock=clock
        )
        #: latest signals per cluster — the stream healer's verb context
        #: (e.g. which brokers were dead when the episode opened)
        self._stream_signals: dict[str, dict] = {}
        #: True while a periodic poll round is being mirrored onto the
        #: stream — the healer must stay silent there (drain owns verbs)
        self._poll_window = False

    # ----- intervals --------------------------------------------------------

    def interval_ms(self, type_: AnomalyType) -> int:
        key = {
            AnomalyType.GOAL_VIOLATION: "goal.violation.detection.interval.ms",
            AnomalyType.METRIC_ANOMALY: "metric.anomaly.detection.interval.ms",
            AnomalyType.DISK_FAILURE: "disk.failure.detection.interval.ms",
            AnomalyType.TOPIC_ANOMALY: "topic.anomaly.detection.interval.ms",
        }.get(type_)
        if key is not None:
            v = self.config[key]
            if v and v > 0:
                return v
        if type_ is AnomalyType.BROKER_FAILURE:
            return self.config["broker.failure.detection.backoff.ms"]
        return self.config["anomaly.detection.interval.ms"]

    # ----- lifecycle --------------------------------------------------------

    def start_detection(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="AnomalyDetectorManager", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        min_interval = min(self.interval_ms(t) for t in AnomalyType)
        next_run = {t: 0 for t in AnomalyType}
        while not self._stop.wait(min_interval / 1000.0):
            now = self.clock()
            due = [t for t in AnomalyType if now >= next_run[t]]
            for t in due:
                next_run[t] = now + self.interval_ms(t)
            try:
                self.run_once(due)
            except Exception:
                log.exception("anomaly detection round failed")

    # ----- one detection round (synchronous; ref detector schedules) --------

    def run_once(self, types: list[AnomalyType] | None = None) -> list[dict]:
        """Run the given detectors (default: all), drain the queue through
        the notifier, and return the decisions taken this round."""
        now = self.clock()
        # Detection and queue pushes hold the lock briefly; the drain —
        # which may run a full self-healing optimization — must NOT hold it,
        # or state() (the REST thread) blocks for the whole heal.
        round_found: list[Anomaly] = []
        for t in types if types is not None else list(AnomalyType):
            detector = self.detectors[t]
            try:
                found = detector.detect(now)
            except Exception:
                log.exception("detector %s failed", t.name)
                continue
            round_found.extend(found)
            with self._lock:
                for anomaly in found:
                    self.metrics[anomaly.type] += 1
                    heapq.heappush(self._queue, (now, anomaly))
        decisions = self._drain(now)
        try:
            self._observe_poll_round(round_found, decisions, now)
        except Exception:  # noqa: BLE001 — mirroring must never break a round
            log.exception("stream mirror of the poll round failed")
        return decisions

    def _drain(self, now_ms: int) -> list[dict]:
        with self._drain_lock:  # one drain at a time; state() stays unblocked
            with self._lock:
                ready: list[tuple[int, Anomaly]] = []
                later: list[tuple[int, Anomaly]] = []
                while self._queue:
                    item = heapq.heappop(self._queue)
                    (ready if item[0] <= now_ms else later).append(item)
                for item in later:
                    heapq.heappush(self._queue, item)

            decisions: list[dict] = []
            requeue: list[tuple[int, Anomaly]] = []
            for _, anomaly in ready:
                if not self._still_valid(anomaly):
                    decisions.append(
                        {
                            "anomaly": anomaly.to_json(),
                            "action": Action.IGNORE.value,
                            "timeMs": now_ms,
                            "resolved": True,
                        }
                    )
                    continue
                result = self.notifier.on_anomaly(anomaly, now_ms)
                record = {
                    "anomaly": anomaly.to_json(),
                    "action": result.action.value,
                    "timeMs": now_ms,
                }
                if result.action is Action.CHECK:
                    requeue.append((now_ms + result.delay_ms, anomaly))
                elif result.action is Action.FIX and self.facade is not None:
                    try:
                        started = anomaly.fix(self.facade)
                        record["selfHealingStarted"] = started
                        if started:
                            with self._lock:
                                self.num_self_healing_started += 1
                    except Exception as e:
                        log.exception("self-healing fix failed")
                        record["selfHealingStarted"] = False
                        record["fixError"] = str(e)
                decisions.append(record)

            with self._lock:
                for item in requeue:
                    heapq.heappush(self._queue, item)
                self.history.extend(decisions)
                del self.history[:-HISTORY_LIMIT]
            return decisions

    def _still_valid(self, anomaly: Anomaly) -> bool:
        """Re-validate a (possibly requeued) anomaly against current state —
        a broker that came back inside the grace window must not be healed
        (ref: CHECK re-detects before acting)."""
        from ccx.detector.anomalies import BrokerFailures

        if isinstance(anomaly, BrokerFailures):
            current = self.detectors[AnomalyType.BROKER_FAILURE]._failed_since
            anomaly.failed_brokers = {
                b: t for b, t in anomaly.failed_brokers.items() if b in current
            }
            return bool(anomaly.failed_brokers)
        return True

    # ----- the live-stream loop (ISSUE 20) ----------------------------------

    def observe_stream(self, cluster: str, signals: dict,
                       t_s: float | None = None) -> dict:
        """Feed one serving window's live signals to the stream detector
        (SLO accounting + classification + one-verb-per-episode healing).
        ``t_s`` defaults to the manager clock, in seconds."""
        if t_s is None:
            t_s = self.clock() / 1000.0
        self._stream_signals[cluster] = dict(signals)
        return self.stream.observe(cluster, signals, t_s)

    def _observe_poll_round(self, found: list, decisions: list,
                            now_ms: int) -> None:
        """Mirror one periodic detection round onto the live-stream
        detector as a single SLO window (service mode's live feed). The
        queue drain owns healing here — notifier grace, alerts, backoff
        — so the stream must NEVER fire a second facade verb: episodes
        open/close from the poll detectors' findings, and an episode is
        marked fired only when this round's drain started the heal."""
        if not self.stream.enabled:
            return
        from ccx.detector.anomalies import BrokerFailures, GoalViolations

        dead: set[int] = set()
        goals = 0
        for a in found:
            if isinstance(a, BrokerFailures):
                dead.update(a.failed_brokers)
            elif isinstance(a, GoalViolations):
                goals += len(a.fixable_violated_goals)
        signals = {
            # a poll round is not a serving window: warm/verified/wall
            # are vacuously good (absent wall_s counts as a latency
            # MISS), only violation_free carries signal here
            "warm": True, "verified": True, "wall_s": 0.0,
            "dead_brokers": tuple(sorted(dead)),
            "goal_violations": goals,
        }
        t_s = now_ms / 1000.0
        self._stream_signals[POLL_CLUSTER] = signals
        self._poll_window = True
        try:
            self.stream.observe(POLL_CLUSTER, signals, t_s)
        finally:
            self._poll_window = False
        healed = [d for d in decisions if d.get("selfHealingStarted")]
        if healed:
            types = {d["anomaly"].get("type") for d in healed}
            verb = ("remove_brokers" if "BROKER_FAILURE" in types
                    else "rebalance")
            self.stream.note_fired(POLL_CLUSTER, verb, t_s)

    def _stream_heal(self, cluster: str, family: str, cause: str) -> str | None:
        """Fire the facade anomaly verb for a stream-classified episode —
        the same dispatch the queue path's ``anomaly.fix`` uses, so the
        verb lands with ``self_healing=True`` (urgent fleet priority)."""
        if self._poll_window:
            # poll-round mirror: the queue drain owns healing (grace /
            # alerts / backoff) — ``note_fired`` mirrors its verb, the
            # stream never dispatches a second one
            return None
        if self.facade is None:
            return None
        from ccx.detector.anomalies import BrokerFailures, GoalViolations

        now = self.clock()
        signals = self._stream_signals.get(cluster) or {}
        dead = tuple(signals.get("dead_brokers") or ())
        if family == "broker_failure" and dead:
            anomaly: Anomaly = BrokerFailures(
                detection_ms=now,
                failed_brokers={int(b): now for b in dead},
            )
            verb = "remove_brokers"
        else:
            anomaly = GoalViolations(
                detection_ms=now,
                fixable_violated_goals=(f"stream:{family}",),
            )
            verb = "rebalance"
        started = anomaly.fix(self.facade)
        if started:
            with self._lock:
                self.num_self_healing_started += 1
        return verb if started else None

    # ----- state ------------------------------------------------------------

    def state(self) -> dict:
        with self._lock:
            return {
                "selfHealingEnabled": {
                    t.name: v
                    for t, v in self.notifier.self_healing_enabled().items()
                },
                "recentAnomalies": self.history[-20:],
                "metrics": {t.name: n for t, n in self.metrics.items()},
                "numSelfHealingStarted": self.num_self_healing_started,
                "pendingChecks": len(self._queue),
                # VIEWER-safe stream-detector + SLO summary (ISSUE 20)
                "slo": self.stream.state(),
            }
