"""AnomalyNotifier SPI — decide FIX / CHECK / IGNORE per anomaly.

Parity: ``detector/notifier/{AnomalyNotifier,SelfHealingNotifier}.java``
(SURVEY.md C30): the notifier is the policy layer between detection and
self-healing — per-anomaly-type enable switches, and for broker failures the
two grace thresholds ``broker.failure.alert.threshold.ms`` (alert after) and
``broker.failure.self.healing.threshold.ms`` (auto-fix after). Webhook
flavors (Slack/MS Teams/Alerta in the reference) are modeled by
``WebhookSelfHealingNotifier`` posting JSON to a configurable sink callable —
transport-free so tests and operators can wire anything.
"""

from __future__ import annotations

import dataclasses
import enum

from ccx.detector.anomalies import Anomaly, AnomalyType, BrokerFailures


class Action(enum.Enum):
    IGNORE = "IGNORE"
    CHECK = "CHECK"   # re-evaluate after delay_ms
    FIX = "FIX"


@dataclasses.dataclass(frozen=True)
class NotifierResult:
    action: Action
    delay_ms: int = 0

    @classmethod
    def ignore(cls) -> "NotifierResult":
        return cls(Action.IGNORE)

    @classmethod
    def check(cls, delay_ms: int) -> "NotifierResult":
        return cls(Action.CHECK, delay_ms)

    @classmethod
    def fix(cls) -> "NotifierResult":
        return cls(Action.FIX)


class AnomalyNotifier:
    """SPI (ref C30)."""

    def configure(self, config) -> None:
        pass

    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> NotifierResult:
        raise NotImplementedError

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}


class SelfHealingNotifier(AnomalyNotifier):
    """Ref SelfHealingNotifier: grace windows for broker failures, a master
    self-healing switch, per-type overrides."""

    def __init__(self, config=None) -> None:
        self.enabled: dict[AnomalyType, bool] = {t: False for t in AnomalyType}
        self.alert_threshold_ms = 900_000
        self.self_healing_threshold_ms = 1_800_000
        self.alerts: list[dict] = []  # alert log (webhooks subclass and send)
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        master = config["self.healing.enabled"]
        self.enabled = {t: master for t in AnomalyType}
        self.alert_threshold_ms = config["broker.failure.alert.threshold.ms"]
        self.self_healing_threshold_ms = config[
            "broker.failure.self.healing.threshold.ms"
        ]

    def self_healing_enabled(self) -> dict[AnomalyType, bool]:
        return dict(self.enabled)

    def alert(self, anomaly: Anomaly, auto_fix_triggered: bool, now_ms: int) -> None:
        self.alerts.append(
            {
                "anomaly": anomaly.to_json(),
                "selfHealingStarted": auto_fix_triggered,
                "timeMs": now_ms,
            }
        )

    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> NotifierResult:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly, now_ms)
        if not self.enabled.get(anomaly.type, False):
            self.alert(anomaly, False, now_ms)
            return NotifierResult.ignore()
        self.alert(anomaly, True, now_ms)
        return NotifierResult.fix()

    def _on_broker_failure(self, anomaly: BrokerFailures, now_ms: int) -> NotifierResult:
        """The reference's two-stage grace logic: before the alert threshold
        stay quiet and re-check; between alert and self-healing thresholds
        alert and re-check; past the self-healing threshold auto-fix (if
        enabled for BROKER_FAILURE)."""
        if not anomaly.failed_brokers:
            return NotifierResult.ignore()
        earliest = min(anomaly.failed_brokers.values())
        alert_at = earliest + self.alert_threshold_ms
        heal_at = earliest + self.self_healing_threshold_ms
        if now_ms < alert_at:
            return NotifierResult.check(alert_at - now_ms)
        can_heal = self.enabled.get(AnomalyType.BROKER_FAILURE, False)
        if now_ms < heal_at:
            self.alert(anomaly, False, now_ms)
            return (
                NotifierResult.check(heal_at - now_ms)
                if can_heal
                else NotifierResult.ignore()
            )
        self.alert(anomaly, can_heal, now_ms)
        return NotifierResult.fix() if can_heal else NotifierResult.ignore()


class WebhookSelfHealingNotifier(SelfHealingNotifier):
    """Alert sink over an injected callable (the Slack/MS Teams/Alerta
    notifiers of the reference, transport abstracted)."""

    def __init__(self, sink=None, config=None) -> None:
        super().__init__(config)
        self.sink = sink or (lambda payload: None)

    def alert(self, anomaly, auto_fix_triggered, now_ms) -> None:
        super().alert(anomaly, auto_fix_triggered, now_ms)
        self.sink(self.alerts[-1])
