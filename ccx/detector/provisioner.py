"""Provisioner SPI — rightsizing verdicts and recommendations.

Parity: ``analyzer/ProvisionStatus``/``ProvisionRecommendation`` +
``detector/BasicProvisioner.java`` behind the ``rightsize`` endpoint
(SURVEY.md C21): given an optimization result, decide whether the cluster is
RIGHT_SIZED / UNDER_PROVISIONED / OVER_PROVISIONED and recommend broker
count changes.
"""

from __future__ import annotations

import dataclasses
import enum
import math

import numpy as np

from ccx.common.resources import NUM_RESOURCES, Resource


class ProvisionStatus(enum.Enum):
    RIGHT_SIZED = "RIGHT_SIZED"
    UNDER_PROVISIONED = "UNDER_PROVISIONED"
    OVER_PROVISIONED = "OVER_PROVISIONED"
    UNDECIDED = "UNDECIDED"


@dataclasses.dataclass(frozen=True)
class ProvisionRecommendation:
    status: ProvisionStatus
    num_brokers_to_add: int = 0
    num_brokers_to_remove: int = 0
    reason: str = ""

    def to_json(self) -> dict:
        return {
            "status": self.status.value,
            "numBrokersToAdd": self.num_brokers_to_add,
            "numBrokersToRemove": self.num_brokers_to_remove,
            "reason": self.reason,
        }


class BasicProvisioner:
    """Default `provisioner.class` (ref BasicProvisioner): capacity-headroom
    arithmetic on the tensor model — under-provisioned when any resource's
    cluster-wide utilization exceeds its capacity threshold even if perfectly
    balanced; over-provisioned when the peak resource would still fit under
    threshold on fewer brokers."""

    def __init__(self, config=None) -> None:
        self.thresholds = {
            Resource.CPU: 0.7, Resource.NW_IN: 0.8,
            Resource.NW_OUT: 0.8, Resource.DISK: 0.8,
        }
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        self.thresholds = {
            Resource.CPU: config["cpu.capacity.threshold"],
            Resource.NW_IN: config["network.inbound.capacity.threshold"],
            Resource.NW_OUT: config["network.outbound.capacity.threshold"],
            Resource.DISK: config["disk.capacity.threshold"],
        }

    def rightsize(self, model) -> ProvisionRecommendation:
        alive = np.asarray(model.broker_valid & model.broker_alive)
        cap = np.asarray(model.broker_capacity)            # [RES, B]
        load = np.asarray(model.replica_load).sum(axis=(1, 2))  # total per RES
        total_cap = (cap * alive[None, :]).sum(axis=1)
        n_alive = int(alive.sum())
        if n_alive == 0:
            return ProvisionRecommendation(
                ProvisionStatus.UNDECIDED, reason="no alive brokers"
            )
        per_broker_cap = total_cap / n_alive
        worst_add = 0
        worst_remove = n_alive
        binding = None
        for r in range(NUM_RESOURCES):
            thr = self.thresholds[Resource(r)]
            usable_per_broker = per_broker_cap[r] * thr
            if usable_per_broker <= 0:
                continue
            needed = math.ceil(load[r] / usable_per_broker)
            if needed - n_alive > worst_add:
                worst_add = needed - n_alive
                binding = Resource(r)
            worst_remove = min(worst_remove, n_alive - needed)
        if worst_add > 0:
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED,
                num_brokers_to_add=worst_add,
                reason=f"{binding.name} demand exceeds usable capacity",
            )
        # keep one spare broker of headroom before calling it over-provisioned
        if worst_remove > 1:
            return ProvisionRecommendation(
                ProvisionStatus.OVER_PROVISIONED,
                num_brokers_to_remove=worst_remove - 1,
                reason="all resources fit under threshold on fewer brokers",
            )
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED)
