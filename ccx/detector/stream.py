"""Live-stream anomaly detection — the closing of the control loop.

The seed-ported detectors (``ccx.detector.detectors``) poll the load
monitor on fixed intervals, the way the reference's
``AnomalyDetectorManager`` does. But rounds 5-18 already put a richer
signal stream on the wire — chunk-heartbeat energies, ``warm_pressure``
bands banked with every placement, goal-violation and fleet/devmem
gauges in ``ccx.common.metrics`` — and nothing consumed it. This module
subscribes to that stream and closes the loop:

1. **classify** each serving window with seeded-deterministic rules
   (fixed thresholds, fixed family priority — the same signal stream
   replays to the same decisions, the property every soak gate and test
   relies on);
2. **heal**: on the first classified violation open a healing episode
   (ONE per cluster — a persistent violation must not storm the facade
   with verbs) and fire the healer callback once, at urgent priority in
   the manager wiring;
3. **forecast**: fit a linear trend to the drift history of each
   cluster's warm-pressure band and, when the trend crosses the
   threshold within the horizon, pre-warm the cluster's base via
   ``PlacementStore`` *before* the violation lands (the consumer-group
   autoscaler move: predict from the history you already bank);
4. **account**: every window feeds the windowed SLO engine
   (``ccx.common.slo``), every decision rides the flight recorder as a
   structured healing-event timeline (detected -> fired -> recovered,
   with cause attribution) plus the labeled Prometheus families
   ``ccx_time_to_heal_seconds{family}`` / ``ccx_slo_burn_rate{objective}``.

The detector is transport-agnostic: the facade's
``AnomalyDetectorManager`` wires the healer to the existing anomaly
verbs, ``bench.py --soak`` wires it to an urgent warm re-propose.
"""

from __future__ import annotations

import random

from ccx.common.slo import SloEngine, SloObjectives

#: classification families, in FIXED priority order — when several rules
#: trip in one window, the first match is the episode's family (the
#: cause attribution is deterministic, never racy)
FAMILIES = (
    "broker_failure",
    "devmem_pressure",
    "goal_violation",
    "cold_serve",
    "latency_burst",
    "pressure_surge",
)

#: family -> facade verb the manager-wired healer fires (ref: the
#: anomaly classes' ``fix`` dispatch). The bench healer substitutes an
#: urgent warm re-propose for all of them.
FAMILY_VERB = {
    "broker_failure": "remove_brokers",
    "devmem_pressure": "rebalance",
    "goal_violation": "rebalance",
    "cold_serve": "rebalance",
    "latency_burst": "rebalance",
    "pressure_surge": "rebalance",
}


def _cfg(config, key, default):
    try:
        return config[key]
    except Exception:  # noqa: BLE001 — absent key (plain dict / None)
        return default


def _default_prewarm(cluster: str) -> bool:
    """Touch the cluster's banked warm base at raised priority: the
    ledger LRU-refreshes and re-prices it, so a predicted violation
    finds the base resident instead of evicted."""
    try:
        from ccx.search.incremental import STORE

        return STORE.get(cluster, priority=1, job=f"prewarm-{cluster}") \
            is not None
    except Exception:  # noqa: BLE001 — prewarm is best-effort
        return False


class StreamDetector:
    """Seeded-deterministic anomaly classification over the live signal
    stream, with one-verb-per-episode healing and SLO accounting.

    ``observe(cluster, signals, t_s)`` is the single entry point — call
    it once per serving window with whatever signals are flowing:

    - ``warm`` / ``verified`` / ``wall_s`` — the window outcome;
    - ``dead_brokers`` — tuple of dead broker ids (structural signal);
    - ``goal_violations`` — count of violated goals on the window;
    - ``pressure`` — the warm-pressure band scalar (mean top-band
      broker pressure from the banked ``warm_pressure`` stack);
    - ``energy`` — last chunk-heartbeat energy (tier-0 lex cost);
    - ``devmem_within_budget`` — the unified ledger's verdict;
    - ``fault`` — injected-fault attribution (chaos seam), when known.

    Absent signals are treated as healthy; the rules never crash on a
    partial stream.
    """

    def __init__(self, config=None, healer=None, prewarmer=None,
                 clock=None, objectives: SloObjectives | None = None) -> None:
        self.enabled = bool(_cfg(config, "detector.stream.enabled", True))
        self.seed = int(_cfg(config, "detector.stream.seed", 0))
        #: consecutive clean windows that close an episode (the FIRST of
        #: the streak stamps t_recovered — "first verified-clean window")
        self.clean_windows = max(
            int(_cfg(config, "detector.stream.clean.windows", 2)), 1
        )
        self.pressure_threshold = float(
            _cfg(config, "detector.stream.pressure.threshold", 0.75)
        )
        self.forecast_windows = max(
            int(_cfg(config, "detector.stream.forecast.windows", 8)), 2
        )
        self.forecast_horizon = max(
            int(_cfg(config, "detector.stream.forecast.horizon.windows", 3)),
            1,
        )
        self.slo = SloEngine(
            objectives or SloObjectives.from_config(config)
        )
        self.healer = healer
        self.prewarmer = prewarmer or _default_prewarm
        self.clock = clock
        #: deterministic tie-break / jitter source — NEVER consulted for
        #: classification (rules are pure thresholds); reserved for
        #: sampling decisions so reruns stay replayable
        self.rng = random.Random(self.seed)
        #: cluster -> pressure history (drift trend the forecast fits)
        self._pressure: dict[str, list[float]] = {}
        #: cluster -> consecutive clean windows since the verb fired
        self._clean_streak: dict[str, int] = {}
        #: cluster -> t of the FIRST clean window of the current streak
        self._clean_since: dict[str, float] = {}
        #: cluster -> first violating-signal time for a not-yet-opened
        #: episode (detection latency measurement starts here)
        self._first_signal: dict[str, float] = {}
        #: clusters whose forecast already pre-warmed (re-armed when the
        #: prediction clears) — one prewarm per predicted crossing
        self._forecast_armed: set[str] = set()
        self._prewarms = 0
        self.metrics = {
            "detected": 0, "fired": 0, "recovered": 0, "forecasts": 0,
        }

    # ----- classification rules (seeded-deterministic) ----------------------

    def classify(self, signals: dict) -> list[tuple[str, str]]:
        """(family, cause) list for one window's signals, in family
        priority order. Pure function of the signals — same stream,
        same verdicts."""
        out: list[tuple[str, str]] = []
        dead = tuple(signals.get("dead_brokers") or ())
        if dead:
            out.append(("broker_failure", f"dead brokers {list(dead)}"))
        if signals.get("devmem_within_budget") is False:
            out.append(
                ("devmem_pressure", "device-memory ledger over budget")
            )
        gv = int(signals.get("goal_violations") or 0)
        if gv > 0:
            out.append(("goal_violation", f"{gv} violated goal(s)"))
        if not signals.get("verified", True) or (
            signals.get("warm") is False and signals.get("cold_fallback")
        ):
            why = signals.get("fault") or (
                "unverified window" if not signals.get("verified", True)
                else "cold fallback (warm base lost)"
            )
            out.append(("cold_serve", str(why)))
        wall = signals.get("wall_s")
        if wall is not None and wall > self.slo.objectives.latency_budget_s:
            out.append((
                "latency_burst",
                f"wall {float(wall):.3f}s over "
                f"{self.slo.objectives.latency_budget_s:.3f}s budget",
            ))
        p = signals.get("pressure")
        if p is not None and float(p) >= self.pressure_threshold:
            out.append((
                "pressure_surge",
                f"pressure {float(p):.3f} >= "
                f"{self.pressure_threshold:.3f}",
            ))
        return out

    # ----- drift-history forecast -------------------------------------------

    def _forecast(self, cluster: str, t_s: float) -> dict | None:
        """Least-squares trend over the pressure history; pre-warm when
        the extrapolation crosses the threshold within the horizon."""
        hist = self._pressure.get(cluster)
        if not hist or len(hist) < self.forecast_windows:
            return None
        ys = hist[-self.forecast_windows:]
        n = len(ys)
        xs = range(n)
        mx = (n - 1) / 2.0
        my = sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
        slope = (sxy / sxx) if sxx else 0.0
        predicted = ys[-1] + slope * self.forecast_horizon
        if ys[-1] >= self.pressure_threshold:
            return None  # already violating: detection's job, not forecast's
        if predicted < self.pressure_threshold:
            self._forecast_armed.discard(cluster)
            return None
        if cluster in self._forecast_armed:
            return None  # one prewarm per predicted crossing
        self._forecast_armed.add(cluster)
        self.metrics["forecasts"] += 1
        prewarmed = False
        try:
            prewarmed = bool(self.prewarmer(cluster))
        except Exception:  # noqa: BLE001 — prewarm is best-effort
            prewarmed = False
        if prewarmed:
            self._prewarms += 1
        event = {
            "cluster": cluster,
            "predicted": round(predicted, 4),
            "slope": round(slope, 5),
            "horizonWindows": self.forecast_horizon,
            "prewarmed": prewarmed,
        }
        self._healing_record("forecast", t_s, **event)
        return event

    # ----- the timeline + metrics sinks -------------------------------------

    def _healing_record(self, phase: str, t_s: float, **attrs) -> None:
        """One structured healing-event record on the flight recorder
        (and every tracer listener): the timeline a dead soak run's
        recording still names."""
        try:
            from ccx.common.tracing import TRACER

            TRACER.healing_event(phase, t=round(float(t_s), 3), **attrs)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass

    def _publish_burn_rates(self) -> None:
        try:
            from ccx.common.metrics import REGISTRY

            for obj, burns in self.slo.burn_rates().items():
                v = burns["short"]
                if v is None:
                    continue
                REGISTRY.set_gauge(
                    "slo-burn-rate", float(v),
                    labels={"objective": obj},
                    help="short-window SLO burn rate per objective "
                         "(error rate over error budget; 1.0 spends "
                         "the budget exactly)",
                )
        except Exception:  # noqa: BLE001
            pass

    def _observe_time_to_heal(self, family: str, tth_s: float) -> None:
        try:
            from ccx.common.metrics import REGISTRY

            REGISTRY.histogram(
                "time-to-heal-seconds",
                help="first violating signal to first verified-clean "
                     "window, per anomaly family",
                labels={"family": family},
            ).observe(float(tth_s))
        except Exception:  # noqa: BLE001
            pass

    # ----- the entry point ---------------------------------------------------

    def observe(self, cluster: str, signals: dict, t_s: float) -> dict:
        """Account one serving window and run the control loop. Returns
        the decision record: classification, episode state, and whether
        a verb was fired (and which)."""
        if not self.enabled:
            return {"enabled": False}
        violations = self.classify(signals)
        p = signals.get("pressure")
        if p is not None:
            self._pressure.setdefault(cluster, []).append(float(p))
            del self._pressure[cluster][:-max(self.forecast_windows * 4, 64)]
        forecast = self._forecast(cluster, t_s)
        good = self.slo.observe(
            cluster,
            warm=bool(signals.get("warm")),
            verified=bool(signals.get("verified")),
            wall_s=signals.get("wall_s"),
            violation_free=not violations,
        )
        decision: dict = {
            "cluster": cluster,
            "violations": violations,
            "good": good,
            "fired": False,
            "verb": None,
            "episode": None,
        }
        if forecast is not None:
            decision["forecast"] = forecast
        ep = self.slo.episode(cluster)
        if violations:
            family, cause = violations[0]
            self._clean_streak[cluster] = 0
            self._clean_since.pop(cluster, None)
            if ep is None:
                first = self._first_signal.pop(cluster, t_s)
                ep = self.slo.open_episode(
                    cluster, family, cause,
                    t_first_signal_s=first, t_detected_s=t_s,
                )
                self.metrics["detected"] += 1
                self._healing_record(
                    "detected", t_s, cluster=cluster, family=family,
                    cause=cause, episode=ep.episode_id,
                )
                verb = None
                if self.healer is not None:
                    try:
                        verb = self.healer(cluster, family, cause)
                    except Exception as e:  # noqa: BLE001 — a failed
                        # verb leaves the episode open; the next clean
                        # windows (or the soak gate) decide its fate
                        verb = None
                        self._healing_record(
                            "fire-failed", t_s, cluster=cluster,
                            family=family, episode=ep.episode_id,
                            error=f"{type(e).__name__}: {e}",
                        )
                if verb is not None:
                    self.slo.mark_fired(cluster, verb, t_s)
                    self.metrics["fired"] += 1
                    self._healing_record(
                        "fired", t_s, cluster=cluster, family=family,
                        verb=verb, episode=ep.episode_id,
                    )
                    decision["fired"] = True
                    decision["verb"] = verb
            # else: episode already open — one verb per episode, the
            # persistent violation only extends it
            decision["episode"] = ep.episode_id if ep is not None else None
        else:
            self._first_signal.pop(cluster, None)
            if ep is not None:
                # clean window while an episode is open: recovery needs
                # `clean_windows` consecutive ones; t_recovered is the
                # FIRST of the streak (first verified-clean window)
                streak = self._clean_streak.get(cluster, 0) + 1
                self._clean_streak[cluster] = streak
                self._clean_since.setdefault(cluster, t_s)
                if streak >= self.clean_windows:
                    t_rec = self._clean_since.pop(cluster, t_s)
                    closed = self.slo.mark_recovered(cluster, t_rec)
                    self._clean_streak.pop(cluster, None)
                    if closed is not None:
                        self.metrics["recovered"] += 1
                        tth = closed.time_to_heal_s
                        if tth is not None:
                            self._observe_time_to_heal(closed.family, tth)
                        self._healing_record(
                            "recovered", t_rec, cluster=cluster,
                            family=closed.family, verb=closed.verb,
                            episode=closed.episode_id,
                            timeToHealS=(
                                None if tth is None else round(tth, 3)
                            ),
                        )
                        decision["recovered"] = closed.episode_id
        self._publish_burn_rates()
        return decision

    def note_fired(self, cluster: str, verb: str, t_s: float) -> bool:
        """Mark an open, not-yet-fired episode as healed by an EXTERNAL
        actor — the queue-path drain in service poll mode, which owns
        notifier grace/backoff and must stay the only verb source there.
        The one-verb accounting and the timeline mirror the heal the
        stream itself did not fire."""
        ep = self.slo.episode(cluster)
        if ep is None or ep.verb is not None:
            return False
        self.slo.mark_fired(cluster, verb, t_s)
        self.metrics["fired"] += 1
        self._healing_record(
            "fired", t_s, cluster=cluster, family=ep.family, verb=verb,
            episode=ep.episode_id,
        )
        return True

    def note_signal(self, cluster: str, t_s: float) -> None:
        """Stamp the FIRST violating signal time for a cluster before
        the window that will carry it is observed — callers that see the
        raw signal earlier than the serving window (e.g. a fault
        injection) use this so time-to-detect starts at the signal, not
        at the observation."""
        self._first_signal.setdefault(cluster, float(t_s))

    # ----- observability -----------------------------------------------------

    def state(self) -> dict:
        """VIEWER-safe block (rides ``AnalyzerState.observability``):
        the SLO summary + detector counters, no paths, no stacks."""
        return {
            "enabled": self.enabled,
            "seed": self.seed,
            "cleanWindows": self.clean_windows,
            "pressureThreshold": self.pressure_threshold,
            "metrics": dict(self.metrics),
            "prewarms": self._prewarms,
            "slo": self.slo.summary(),
        }

    def observability_json(self, limit: int = 32) -> dict:
        """The USER-gated block (GET /observability): state plus the
        healing-event timeline."""
        out = self.state()
        out["timeline"] = self.slo.episodes_json(limit)
        return out
