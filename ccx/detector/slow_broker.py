"""SlowBrokerFinder — latency-percentile broker anomaly detection.

Parity: ``detector/SlowBrokerFinder.java`` (SURVEY.md C29, §5.3): a broker is
*slow* when its log-flush time is high both against its **own history**
(current value above the configured percentile of its window history) and
against the **cluster** (above the cluster-wide mean by a margin), while it
is actually serving traffic (bytes-in above a floor, so idle brokers are not
flagged). Persistent slowness escalates from demotion to removal in the
reference; we carry that via ``fix_by_demotion``.
"""

from __future__ import annotations

import numpy as np

from ccx.detector.anomalies import Anomaly, MetricAnomaly
from ccx.monitor.aggregator import AggregationResult
from ccx.monitor.metricdef import BROKER_METRIC_DEF

_FLUSH = BROKER_METRIC_DEF.metric_info("BROKER_LOG_FLUSH_TIME_MS_MEAN").id
_BYTES_IN = BROKER_METRIC_DEF.metric_info("ALL_TOPIC_BYTES_IN").id


class SlowBrokerFinder:
    """Default `metric.anomaly.finder.class` (ref C29)."""

    def __init__(self, config=None) -> None:
        self.bytes_in_floor_kb_s = 1024.0
        self.flush_threshold_ms = 1000.0
        self.history_percentile = 90.0
        self.cluster_margin = 3.0  # current > margin x cluster mean
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        self.bytes_in_floor_kb_s = config[
            "slow.broker.bytes.in.rate.detection.threshold"
        ]
        self.flush_threshold_ms = config[
            "slow.broker.log.flush.time.threshold.ms"
        ]
        self.history_percentile = config[
            "slow.broker.metric.history.percentile.threshold"
        ]

    def find(self, agg: AggregationResult, metadata, now_ms: int) -> list[Anomaly]:
        if agg.num_windows < 2:
            return []
        flush = agg.values[:, :, _FLUSH]        # [B, W]
        bytes_in = agg.values[:, :, _BYTES_IN]  # [B, W]
        current = flush[:, -1]
        history = flush[:, :-1]
        hist_pct = np.percentile(history, self.history_percentile, axis=1)
        alive = np.array([b.alive for b in metadata.brokers], bool)
        n = min(len(alive), flush.shape[0])
        alive = alive[:n]
        current, hist_pct = current[:n], hist_pct[:n]
        serving = bytes_in[:n, -1] >= self.bytes_in_floor_kb_s
        cluster_mean = float(np.mean(current[alive])) if alive.any() else 0.0
        slow = (
            alive
            & serving
            & (current > self.flush_threshold_ms)
            & (current > hist_pct)
            & (current > self.cluster_margin * max(cluster_mean, 1e-9))
        )
        out: list[Anomaly] = []
        for i in np.nonzero(slow)[0]:
            out.append(
                MetricAnomaly(
                    detection_ms=now_ms,
                    broker_id=metadata.brokers[i].broker_id,
                    metric_name="BROKER_LOG_FLUSH_TIME_MS_MEAN",
                    description=(
                        f"log flush time {current[i]:.1f}ms exceeds "
                        f"p{self.history_percentile:.0f} history "
                        f"{hist_pct[i]:.1f}ms and {self.cluster_margin:.0f}x "
                        f"cluster mean {cluster_mean:.1f}ms"
                    ),
                    fix_by_demotion=True,
                )
            )
        return out
