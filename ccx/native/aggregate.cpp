// Native windowed-scatter kernel for the metric-sample aggregator.
//
// The reference's host-side hot loop #2 (SURVEY.md call stack 3.2) is the
// O(P * W) windowed rollup; in ccx it is the ingest scatter in
// ccx/monitor/aggregator.py. numpy's ufunc.at is an order of magnitude
// slower than a fused single pass at 100k-partition sample batches, so this
// kernel applies all four accumulations (sum, max, count, latest) in one
// cache-friendly sweep. Loaded via ctypes (ccx/native/__init__.py) with a
// transparent numpy fallback when the shared library is unavailable.
//
// Layout contract (matches the aggregator's arrays):
//   sum, mx, latest : double[E, W, M]  (C-contiguous)
//   latest_t        : int64[E, W]
//   count           : int64[E, W]
//   entities, slots : int64[n]  (slots pre-validated: 0 <= slot < W)
//   times           : int64[n]  (rows sorted ascending by time so the
//                                "latest" overwrite is last-write-wins)
//   metrics         : double[n, M]

#include <cstdint>

extern "C" {

void ccx_scatter(double* sum, double* mx, double* latest,
                 std::int64_t* latest_t, std::int64_t* count,
                 const std::int64_t* entities, const std::int64_t* slots,
                 const std::int64_t* times, const double* metrics,
                 std::int64_t n, std::int64_t W, std::int64_t M) {
  for (std::int64_t i = 0; i < n; ++i) {
    const std::int64_t cell = entities[i] * W + slots[i];
    double* srow = sum + cell * M;
    double* xrow = mx + cell * M;
    const double* m = metrics + i * M;
    for (std::int64_t j = 0; j < M; ++j) {
      srow[j] += m[j];
      if (m[j] > xrow[j]) xrow[j] = m[j];
    }
    count[cell] += 1;
    if (times[i] >= latest_t[cell]) {
      latest_t[cell] = times[i];
      double* lrow = latest + cell * M;
      for (std::int64_t j = 0; j < M; ++j) lrow[j] = m[j];
    }
  }
}

// Batch decode of length-prefixed partition samples (ccx/monitor/sampling/
// holders.py serialize_batch framing) into columnar arrays — the warm-start
// path deserializes the full store at boot; a Python struct loop costs
// ~3 us/record, this costs ~0.03.
//   buf: the raw log; out_*: preallocated [capacity] / [capacity, M]
// Returns number of records decoded, or -1 on a framing error.
std::int64_t ccx_decode_partition_samples(
    const unsigned char* buf, std::int64_t len, std::int64_t capacity,
    std::int64_t M, std::int64_t* out_ids, std::int64_t* out_times,
    double* out_metrics) {
  std::int64_t off = 0, rec = 0;
  const std::int64_t head = 3 + 1 + 8 + 8 + 8 + 2;  // magic ver broker part time n
  while (off + 4 <= len && rec < capacity) {
    std::uint32_t rlen;
    __builtin_memcpy(&rlen, buf + off, 4);
    off += 4;
    if (off + rlen > len) return -1;
    const unsigned char* r = buf + off;
    if (rlen < 4) return -1;  // too short for even magic + version
    if (!(r[0] == 'C' && r[1] == 'X' && r[2] == 'P')) {
      off += rlen;  // skip broker samples and other record types
      continue;
    }
    // Validate the record version like the Python deserializer does —
    // a future schema must fail loudly (caller falls back), not misparse.
    if (r[3] > 1) return -1;
    if (static_cast<std::int64_t>(rlen) < head) return -1;
    std::int64_t partition, time_ms;
    std::uint16_t nm;
    __builtin_memcpy(&partition, r + 12, 8);
    __builtin_memcpy(&time_ms, r + 20, 8);
    __builtin_memcpy(&nm, r + 28, 2);
    if (head + 8 * static_cast<std::int64_t>(nm) > rlen) return -1;
    out_ids[rec] = partition;
    out_times[rec] = time_ms;
    const std::int64_t take = nm < M ? nm : M;
    for (std::int64_t j = 0; j < take; ++j) {
      double v;
      __builtin_memcpy(&v, r + head + 8 * j, 8);
      out_metrics[rec * M + j] = v;
    }
    for (std::int64_t j = take; j < M; ++j) out_metrics[rec * M + j] = 0.0;
    ++rec;
    off += rlen;
  }
  return rec;
}

}  // extern "C"
