"""Build the native kernels: ``python -m ccx.native.build``."""

from __future__ import annotations

import os
import subprocess
import sys


def build(quiet: bool = False) -> str:
    src_dir = os.path.dirname(os.path.abspath(__file__))
    out_dir = os.path.join(src_dir, "_build")
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, "libccxnative.so")
    src = os.path.join(src_dir, "aggregate.cpp")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = out + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        src, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=quiet)
    os.replace(tmp, out)  # atomic: concurrent builders never tear the .so
    if not quiet:
        print(f"built {out}")
    return out


if __name__ == "__main__":
    sys.exit(0 if build() else 1)
