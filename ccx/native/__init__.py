"""Native host-side kernels (C++ via ctypes, numpy fallback).

Build with ``python -m ccx.native.build`` (or let the first import try a
quiet on-demand g++ build — the toolchain is a build-time convenience, never
a runtime requirement: every entry point has a numpy fallback).
"""

from __future__ import annotations

import ctypes
import logging
import os

import numpy as np

log = logging.getLogger(__name__)

_LIB_NAME = "libccxnative.so"
_lib: ctypes.CDLL | None = None
_tried = False
_load_lock = __import__("threading").Lock()


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "_build", _LIB_NAME)


def load(build_if_missing: bool = True) -> ctypes.CDLL | None:
    """The shared library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _load_lock:
        if _tried:  # lost the race: another thread already resolved it
            return _lib
        return _load_locked(build_if_missing)


def _load_locked(build_if_missing: bool) -> ctypes.CDLL | None:
    global _lib, _tried
    _tried = True
    path = _lib_path()
    if not os.path.exists(path) and build_if_missing:
        try:
            from ccx.native.build import build

            build(quiet=True)
        except Exception:  # toolchain missing: fall back silently
            log.debug("native build unavailable", exc_info=True)
    if os.path.exists(path):
        try:
            lib = ctypes.CDLL(path)
            pd = ctypes.POINTER(ctypes.c_double)
            pi = ctypes.POINTER(ctypes.c_int64)
            lib.ccx_scatter.restype = None
            lib.ccx_scatter.argtypes = [
                pd, pd, pd, pi, pi, pi, pi, pi, pd,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ]
            lib.ccx_decode_partition_samples.restype = ctypes.c_int64
            lib.ccx_decode_partition_samples.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, pi, pi, pd,
            ]
            _lib = lib
        except OSError:
            log.warning("failed to load %s", path, exc_info=True)
    return _lib


def available() -> bool:
    return load() is not None


def _ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


def scatter(sum_: np.ndarray, mx: np.ndarray, latest: np.ndarray,
            latest_t: np.ndarray, count: np.ndarray,
            entities: np.ndarray, slots: np.ndarray, times: np.ndarray,
            metrics: np.ndarray) -> bool:
    """Fused windowed-scatter; returns False if the caller must use the
    numpy path. Arrays must be C-contiguous with the aggregator's dtypes."""
    lib = load()
    if lib is None:
        return False
    n = entities.shape[0]
    W, M = sum_.shape[1], sum_.shape[2]
    if not (
        sum_.flags.c_contiguous and mx.flags.c_contiguous
        and latest.flags.c_contiguous and latest_t.flags.c_contiguous
        and count.flags.c_contiguous
    ):
        return False
    entities = np.ascontiguousarray(entities, np.int64)
    slots = np.ascontiguousarray(slots, np.int64)
    times = np.ascontiguousarray(times, np.int64)
    metrics = np.ascontiguousarray(metrics, np.float64)
    lib.ccx_scatter(
        _ptr(sum_, ctypes.c_double), _ptr(mx, ctypes.c_double),
        _ptr(latest, ctypes.c_double), _ptr(latest_t, ctypes.c_int64),
        _ptr(count, ctypes.c_int64), _ptr(entities, ctypes.c_int64),
        _ptr(slots, ctypes.c_int64), _ptr(times, ctypes.c_int64),
        _ptr(metrics, ctypes.c_double), n, W, M,
    )
    return True


def decode_partition_samples(buf: bytes, capacity: int, n_metrics: int):
    """(ids, times, metrics) columnar decode of a partition-sample log, or
    None if the native library is unavailable or the log is malformed."""
    lib = load()
    if lib is None:
        return None
    ids = np.empty(capacity, np.int64)
    times = np.empty(capacity, np.int64)
    metrics = np.empty((capacity, n_metrics), np.float64)
    # zero-copy view: the C side only reads, so pass the bytes' own buffer
    view = np.frombuffer(buf, np.uint8)
    n = lib.ccx_decode_partition_samples(
        _ptr(view, ctypes.c_ubyte), len(buf), capacity,
        n_metrics, _ptr(ids, ctypes.c_int64), _ptr(times, ctypes.c_int64),
        _ptr(metrics, ctypes.c_double),
    )
    if n < 0:
        return None
    return ids[:n], times[:n], metrics[:n]
