"""Synthetic cluster fixtures.

Parity: the reference's analyzer tests are built entirely on synthetic
in-memory models — ``common/DeterministicCluster.java`` (canned small models
with exact loads) and ``analyzer/RandomCluster.java`` (parameterized random
models) per SURVEY.md section 4. These generators play the same role for the
tensor model; every test and benchmark config (B1-B5, BASELINE.md) is
produced here, seeded and reproducible.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.resources import NUM_RESOURCES, Resource
from ccx.model.tensor_model import TensorClusterModel, build_model


def small_deterministic() -> TensorClusterModel:
    """A tiny 3-rack / 3-broker / 2-topic model with hand-auditable loads.

    Mirrors the role of DeterministicCluster#smallClusterModel: topic A has
    2 partitions (RF=2), topic B has 1 partition (RF=3). Loads are small
    integers so goal tests can assert exact violation counts.
    """
    # partitions: A-0, A-1, B-0
    assignment = np.array(
        [
            [0, 1, -1],   # A-0 on brokers 0,1
            [1, 2, -1],   # A-1 on brokers 1,2
            [0, 1, 2],    # B-0 on all three
        ],
        np.int32,
    )
    partition_topic = np.array([0, 0, 1], np.int32)
    # loads[res, p]
    leader_load = np.array(
        [
            [20.0, 10.0, 5.0],    # CPU
            [100.0, 50.0, 20.0],  # NW_IN
            [80.0, 40.0, 10.0],   # NW_OUT
            [300.0, 150.0, 60.0],  # DISK
        ],
        np.float32,
    )
    follower_load = leader_load.copy()
    follower_load[Resource.CPU] *= 0.5
    follower_load[Resource.NW_OUT] = 0.0
    broker_capacity = np.tile(
        np.array([[100.0], [2000.0], [2000.0], [5000.0]], np.float32), (1, 3)
    )
    broker_rack = np.array([0, 1, 2], np.int32)
    return build_model(
        assignment=assignment,
        leader_load=leader_load,
        follower_load=follower_load,
        broker_capacity=broker_capacity,
        broker_rack=broker_rack,
        partition_topic=partition_topic,
        pad=False,
    )


@dataclasses.dataclass
class RandomClusterSpec:
    """Knobs mirroring RandomCluster's parameterization (SURVEY.md section 4)."""

    n_brokers: int = 10
    n_racks: int = 3
    n_topics: int = 10
    n_partitions: int = 1000
    min_rf: int = 2
    max_rf: int = 3
    #: mean per-partition loads, per resource (CPU %, KB/s, KB/s, MB)
    mean_load: tuple[float, float, float, float] = (0.2, 80.0, 160.0, 350.0)
    #: broker capacity headroom multiplier over perfectly-balanced load
    capacity_headroom: float = 2.5
    follower_cpu_fraction: float = 0.5
    #: fraction of partitions skewed onto a hot-spot subset of brokers
    skew: float = 0.6
    n_dead_brokers: int = 0
    n_disks: int = 1
    #: brokers per physical host (ref model/Host.java; 1 = every broker its
    #: own host). Hosts never span racks: host ids are assigned within rack
    #: stripes so the rack -> host -> broker tree stays well-formed.
    brokers_per_host: int = 1
    seed: int = 0


def random_cluster(spec: RandomClusterSpec) -> TensorClusterModel:
    """Generate a seeded random cluster with deliberate imbalance.

    ``skew`` concentrates that fraction of replicas on the first
    ~quarter of brokers so a fresh cluster is genuinely unbalanced — the
    optimizer must have work to do, as in RandomClusterTest.
    """
    rng = np.random.default_rng(spec.seed)
    P, B = spec.n_partitions, spec.n_brokers
    R = spec.max_rf

    partition_topic = np.sort(rng.integers(0, spec.n_topics, P)).astype(np.int32)
    rf = rng.integers(spec.min_rf, spec.max_rf + 1, P)

    hot = max(1, B // 4)
    assignment = np.full((P, R), -1, np.int32)
    for p in range(P):
        if rng.random() < spec.skew:
            # biased: first replica from the hot set, rest anywhere
            pool = np.concatenate(
                [rng.permutation(hot)[:1],
                 rng.permutation(B)[: rf[p] * 2]]
            )
            seen: list[int] = []
            for b in pool:
                if b not in seen:
                    seen.append(int(b))
                if len(seen) == rf[p]:
                    break
            assignment[p, : rf[p]] = seen
        else:
            assignment[p, : rf[p]] = rng.choice(B, size=rf[p], replace=False)

    # Log-normal-ish loads: a few heavy partitions, many light ones.
    mean = np.asarray(spec.mean_load, np.float32)
    raw = rng.lognormal(mean=0.0, sigma=1.0, size=(NUM_RESOURCES, P)).astype(
        np.float32
    )
    leader_load = raw * (mean / np.exp(0.5))[:, None]
    follower_load = leader_load.copy()
    follower_load[Resource.CPU] *= spec.follower_cpu_fraction
    follower_load[Resource.NW_OUT] = 0.0

    # Capacity: headroom over the perfectly-balanced per-broker load.
    total = leader_load.sum(axis=1) + follower_load.sum(axis=1) * (rf.mean() - 1)
    per_broker = total / B * spec.capacity_headroom
    broker_capacity = np.tile(per_broker[:, None], (1, B)).astype(np.float32)
    broker_rack = (np.arange(B) % spec.n_racks).astype(np.int32)
    # hosts group same-rack brokers (stripes: rack r holds indices
    # r, r+n_racks, ...), so a host never spans racks
    pos_in_rack = np.arange(B) // spec.n_racks
    host_key = (
        broker_rack.astype(np.int64) * B
        + pos_in_rack // max(spec.brokers_per_host, 1)
    )
    broker_host = np.unique(host_key, return_inverse=True)[1].astype(np.int32)

    broker_alive = np.ones(B, bool)
    if spec.n_dead_brokers:
        dead = rng.choice(B, size=spec.n_dead_brokers, replace=False)
        broker_alive[dead] = False

    disk_capacity = None
    replica_disk = None
    if spec.n_disks > 1:
        # Broker DISK capacity == sum of its disks (JBOD invariant).
        disk_capacity = np.full(
            (B, spec.n_disks),
            per_broker[Resource.DISK] / spec.n_disks,
            np.float32,
        )
        replica_disk = np.where(
            assignment >= 0, rng.integers(0, spec.n_disks, (P, R)), -1
        ).astype(np.int32)

    return build_model(
        assignment=assignment,
        leader_load=leader_load,
        follower_load=follower_load,
        broker_capacity=broker_capacity,
        broker_rack=broker_rack,
        broker_host=broker_host,
        partition_topic=partition_topic,
        broker_alive=broker_alive,
        disk_capacity=disk_capacity,
        replica_disk=replica_disk,
        num_racks=spec.n_racks,
    )


# --- benchmark configs (BASELINE.md B1-B5) ---

def bench_spec(name: str) -> RandomClusterSpec:
    """Named benchmark cluster specs matching BASELINE.json configs."""
    if name == "B1":  # 10 brokers / 1k partitions, replica-distribution only
        return RandomClusterSpec(n_brokers=10, n_partitions=1_000, seed=1)
    if name == "B2":  # default goal stack, 50 brokers
        return RandomClusterSpec(
            n_brokers=50, n_racks=5, n_topics=40, n_partitions=5_000, seed=2
        )
    if name == "B3":  # self-healing: dead broker evacuation
        return RandomClusterSpec(
            n_brokers=20, n_racks=4, n_topics=20, n_partitions=2_000,
            n_dead_brokers=2, seed=3,
        )
    if name == "B4":  # JBOD intra-broker disk rebalance
        return RandomClusterSpec(
            n_brokers=10, n_partitions=1_000, n_disks=4, seed=4
        )
    if name == "B5":  # 1000 brokers / 100k partitions, full stack
        return RandomClusterSpec(
            n_brokers=1_000, n_racks=20, n_topics=500, n_partitions=100_000,
            skew=0.3, seed=5,
        )
    if name == "B6":  # 10k brokers / 1M partitions — the multi-chip rung
        # (ROADMAP "Multi-chip sharded optimizer → B6 scale"): one order
        # of magnitude past B5, the regime the JVM analyzer cannot touch.
        # Padded shapes (P 1,048,576 / B 16,384 — power-of-two buckets)
        # are STABLE across seeds and divide every mesh parts factor up
        # to 64, so the sharded chunk programs never reshape.
        return RandomClusterSpec(
            n_brokers=10_000, n_racks=40, n_topics=2_000,
            n_partitions=1_000_000, skew=0.3, seed=6,
        )
    raise KeyError(name)
