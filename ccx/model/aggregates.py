"""Broker-level aggregates — the tensor equivalent of ClusterModelStats inputs.

The reference walks the object tree to compute per-broker loads and counts
(``model/ClusterModelStats.java``, SURVEY.md C4). Here one fused pass of
segment-sums over the flattened (partition x slot) axis produces every
aggregate the goal stack needs. Everything is pure and vmappable over a batch
of candidate assignments, which is what makes batched annealing possible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ccx.common import costmodel
from ccx.common.resources import NUM_RESOURCES, Resource
from ccx.model.tensor_model import TensorClusterModel


@struct.dataclass
class BrokerAggregates:
    broker_load: jnp.ndarray        # float32[RES, B] role-resolved load
    replica_count: jnp.ndarray      # int32[B]
    leader_count: jnp.ndarray       # int32[B]
    potential_nw_out: jnp.ndarray   # float32[B] if every hosted replica led
    leader_bytes_in: jnp.ndarray    # float32[B] NW_IN of leader replicas only
    topic_replica_count: jnp.ndarray  # int32[T, B]
    topic_leader_count: jnp.ndarray   # int32[T, B]
    disk_load: jnp.ndarray          # float32[B, D]


def broker_aggregates(m: TensorClusterModel) -> BrokerAggregates:
    # On TPU the segment-sum scatter-adds below serialize; the Pallas
    # kernel reformulates them as tiled one-hot MXU matmuls
    # (ccx/ops/mxu_aggregates.py). Takes effect only on the TPU backend
    # AND with CCX_MXU_AGGREGATES=1 set before process start (opt-in until
    # first validated on live hardware — see mxu_aggregates_enabled).
    from ccx.ops.mxu_aggregates import broker_aggregates_mxu, mxu_aggregates_enabled

    if mxu_aggregates_enabled():
        return broker_aggregates_mxu(m)
    return _broker_aggregates_xla(m)


def _broker_aggregates_xla(m: TensorClusterModel) -> BrokerAggregates:
    B, T, D = m.B, m.num_topics, m.D
    valid = m.replica_valid                      # [P, R]
    is_leader = m.is_leader                      # [P, R]

    # Segment ids: invalid slots overflow into bucket B (dropped on slice).
    seg = jnp.where(valid, m.assignment, B).reshape(-1)          # [P*R]

    def bsum(data_flat, num=B + 1):
        return jax.ops.segment_sum(data_flat, seg, num_segments=num)[:B]

    # Role-resolved per-slot loads [RES, P, R] -> broker_load [RES, B].
    slot_load = m.replica_load
    broker_load = jax.vmap(lambda d: bsum(d.reshape(-1)))(slot_load)

    ones = valid.astype(jnp.int32).reshape(-1)
    replica_count = bsum(ones)
    leader_count = bsum(is_leader.astype(jnp.int32).reshape(-1))

    # Potential NW_OUT: leader-role NW_OUT of every hosted replica
    # (parity: ClusterModelStats potential nw-out used by PotentialNwOutGoal).
    pot = jnp.where(valid, m.leader_load[Resource.NW_OUT][:, None], 0.0)
    potential_nw_out = bsum(pot.reshape(-1))

    lbi = jnp.where(is_leader, m.leader_load[Resource.NW_IN][:, None], 0.0)
    leader_bytes_in = bsum(lbi.reshape(-1))

    # (topic, broker) counts via combined segment ids.
    tb = jnp.where(
        valid, m.partition_topic[:, None] * B + m.assignment, T * B
    ).reshape(-1)
    topic_replica_count = jax.ops.segment_sum(
        valid.astype(jnp.int32).reshape(-1), tb, num_segments=T * B + 1
    )[: T * B].reshape(T, B)
    topic_leader_count = jax.ops.segment_sum(
        is_leader.astype(jnp.int32).reshape(-1), tb, num_segments=T * B + 1
    )[: T * B].reshape(T, B)

    # (broker, disk) DISK load for JBOD goals (role-resolved so it always
    # column-sums to broker_load[DISK] even if a caller differentiates
    # leader vs follower disk footprints).
    bd = jnp.where(
        valid & (m.replica_disk >= 0), m.assignment * D + m.replica_disk, B * D
    ).reshape(-1)
    disk_data = slot_load[Resource.DISK]
    disk_load = jax.ops.segment_sum(
        disk_data.reshape(-1), bd, num_segments=B * D + 1
    )[: B * D].reshape(B, D)

    return BrokerAggregates(
        broker_load=broker_load,
        replica_count=replica_count,
        leader_count=leader_count,
        potential_nw_out=potential_nw_out,
        leader_bytes_in=leader_bytes_in,
        topic_replica_count=topic_replica_count,
        topic_leader_count=topic_leader_count,
        disk_load=disk_load,
    )


#: Jitted entry for host-side callers (e.g. hot-partition targeting) — an
#: eager call dispatches every op separately and recomputes per invocation;
#: the jitted form compiles once per shape and fuses the segment-sums.
broker_aggregates_jit = costmodel.instrument("broker-aggregates")(
    jax.jit(broker_aggregates)
)
