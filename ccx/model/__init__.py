from ccx.model.tensor_model import TensorClusterModel  # noqa: F401
from ccx.model.aggregates import BrokerAggregates, broker_aggregates  # noqa: F401
