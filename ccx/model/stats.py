"""ClusterModelStats — summary statistics of a cluster model state.

Parity: ``model/ClusterModelStats.java`` (SURVEY.md C4) is the stats block
the reference's soft goals, tests and operators score against: per-resource
utilization mean/st.dev/min/max over alive brokers, replica / leader-replica
/ topic-replica distribution stats, and potential nw-out. Upstream attaches
it to ``OptimizerResult`` (per-goal stats deltas) and the ``load`` endpoint;
so does this module (ccx.optimizer.OptimizerResult.to_json,
ccx.service.facade.load).

The JSON shape mirrors upstream's ``ClusterModelStats.getJsonStructure``:
``{"metadata": {brokers, replicas, topics}, "statistics": {AVG, STD, MIN,
MAX}}`` with the eight upstream metric keys.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.resources import Resource
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel

#: Upstream stat keys, in upstream order.
STAT_KEYS = (
    "disk",
    "cpu",
    "networkInbound",
    "networkOutbound",
    "potentialNwOut",
    "replicas",
    "leaderReplicas",
    "topicReplicas",
)

_RESOURCE_KEYS = {
    "cpu": Resource.CPU,
    "networkInbound": Resource.NW_IN,
    "networkOutbound": Resource.NW_OUT,
    "disk": Resource.DISK,
}


@dataclasses.dataclass(frozen=True)
class ClusterModelStats:
    """Summary stats over alive brokers (ref: model/ClusterModelStats.java)."""

    n_brokers: int
    n_replicas: int
    n_topics: int
    n_partitions: int
    avg: dict[str, float]
    std: dict[str, float]
    min: dict[str, float]
    max: dict[str, float]
    #: distinct hosts among alive brokers (ref model/Host.java rollup;
    #: equals n_brokers when every broker is its own host)
    n_hosts: int = 0

    def to_json(self) -> dict:
        return {
            "metadata": {
                "brokers": self.n_brokers,
                "hosts": self.n_hosts,
                "replicas": self.n_replicas,
                "topics": self.n_topics,
                "partitions": self.n_partitions,
            },
            "statistics": {
                "AVG": dict(self.avg),
                "STD": dict(self.std),
                "MIN": dict(self.min),
                "MAX": dict(self.max),
            },
        }


def _dist(values: np.ndarray) -> tuple[float, float, float, float]:
    if values.size == 0:
        return 0.0, 0.0, 0.0, 0.0
    return (
        float(values.mean()),
        float(values.std()),
        float(values.min()),
        float(values.max()),
    )


#: module-level jitted aggregate pass — a per-call ``jax.jit`` wrapper
#: re-traces (and re-compiles) on every invocation because the jit cache
#: keys on the wrapper object, not the wrapped function
_AGG_JIT = None


def _agg(m):
    global _AGG_JIT
    if _AGG_JIT is None:
        import jax

        _AGG_JIT = jax.jit(broker_aggregates)
    return _AGG_JIT(m)


def cluster_model_stats(
    m: TensorClusterModel, agg: BrokerAggregates | None = None
) -> ClusterModelStats:
    """Compute the stats block from a model state (one aggregate pass)."""
    if agg is None:
        agg = _agg(m)
    alive = np.asarray(m.broker_valid & m.broker_alive)
    loads = np.asarray(agg.broker_load)              # [RES, B]
    repl = np.asarray(agg.replica_count)
    lead = np.asarray(agg.leader_count)
    pot = np.asarray(agg.potential_nw_out)
    trc = np.asarray(agg.topic_replica_count)        # [T, B]

    avg: dict[str, float] = {}
    std: dict[str, float] = {}
    mn: dict[str, float] = {}
    mx: dict[str, float] = {}

    for key, res in _RESOURCE_KEYS.items():
        avg[key], std[key], mn[key], mx[key] = _dist(loads[res][alive])
    avg["potentialNwOut"], std["potentialNwOut"], mn["potentialNwOut"], mx["potentialNwOut"] = _dist(pot[alive])
    avg["replicas"], std["replicas"], mn["replicas"], mx["replicas"] = _dist(
        repl[alive].astype(np.float64)
    )
    (
        avg["leaderReplicas"],
        std["leaderReplicas"],
        mn["leaderReplicas"],
        mx["leaderReplicas"],
    ) = _dist(lead[alive].astype(np.float64))

    # Topic-replica distribution: per-topic stats across alive brokers,
    # averaged over topics that have replicas (upstream scores the per-topic
    # spread; empty/padding topics carry no signal).
    cells = trc[:, alive].astype(np.float64)         # [T, B_alive]
    has = cells.sum(axis=1) > 0
    if has.any() and cells.shape[1] > 0:
        per_topic = cells[has]
        avg["topicReplicas"] = float(per_topic.mean(axis=1).mean())
        std["topicReplicas"] = float(per_topic.std(axis=1).mean())
        mn["topicReplicas"] = float(per_topic.min(axis=1).mean())
        mx["topicReplicas"] = float(per_topic.max(axis=1).mean())
    else:
        avg["topicReplicas"] = std["topicReplicas"] = 0.0
        mn["topicReplicas"] = mx["topicReplicas"] = 0.0

    return ClusterModelStats(
        n_brokers=int(alive.sum()),
        n_replicas=int(np.asarray(m.n_replicas)),
        n_topics=int(has.sum()) if cells.shape[1] > 0 else 0,
        n_partitions=int(np.asarray(m.n_partitions)),
        avg=avg,
        std=std,
        min=mn,
        max=mx,
        n_hosts=int(np.unique(np.asarray(m.broker_host)[alive]).size),
    )


def host_rollup(
    m: TensorClusterModel, agg: BrokerAggregates | None = None
) -> dict[int, dict[str, float]]:
    """Per-HOST aggregates over alive brokers (ref model/Host.java: a host
    aggregates its brokers' capacity and load; multi-broker hosts appear as
    one row). Keys are host ids; values carry summed loads, capacity, and
    replica/leader counts — the host axis of kafka_cluster_state/load."""
    if agg is None:
        agg = _agg(m)
    alive = np.asarray(m.broker_valid & m.broker_alive)
    hosts = np.asarray(m.broker_host)
    loads = np.asarray(agg.broker_load)
    caps = np.asarray(m.broker_capacity)
    repl = np.asarray(agg.replica_count)
    lead = np.asarray(agg.leader_count)
    out: dict[int, dict[str, float]] = {}
    for h in np.unique(hosts[alive]):
        sel = alive & (hosts == h)
        row = {"brokers": float(sel.sum())}
        for key, res in _RESOURCE_KEYS.items():
            row[key] = float(loads[res][sel].sum())
            row[key + "Capacity"] = float(caps[res][sel].sum())
        row["replicas"] = float(repl[sel].sum())
        row["leaderReplicas"] = float(lead[sel].sum())
        out[int(h)] = row
    return out


def balancedness_score(stats: ClusterModelStats) -> float:
    """[0, 100] balancedness summary (ref: OptimizerResult's on-demand
    balancedness score): 100 when every tracked distribution has zero spread;
    decays with the mean coefficient of variation across stat keys."""
    cvs = []
    for key in STAT_KEYS:
        a = stats.avg[key]
        if a > 1e-12:
            cvs.append(stats.std[key] / a)
    if not cvs:
        return 100.0
    return float(100.0 / (1.0 + float(np.mean(cvs))))
