"""Snapshot codec — the ClusterModel wire/file format.

SURVEY.md §7.2: the tensor ClusterModel round-trips through a snapshot
schema that is also the gRPC payload of the sidecar (JVM → TPU hop of the
north star, BASELINE.json:5). Two encodings share one schema:

* **JSON** — human-readable files for the CLI (`ccx propose --snapshot f.json`)
  and fixtures; arrays as nested lists.
* **msgpack** — the wire format: arrays as raw little-endian buffers with
  dtype/shape headers (zero-copy into numpy), ~10x smaller/faster than JSON
  at 100k partitions, where snapshot transfer is a real cost (SURVEY.md
  §7.4 "snapshot transfer").

Delta snapshots (``delta_encode``/``delta_apply``) send only changed fields
keyed by the base generation — the mitigation SURVEY.md prescribes for
repeated 100k-partition transfers over DCN.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

from ccx.model.tensor_model import TensorClusterModel, build_model

#: fields build_model accepts directly (arrays); kept in one place so the
#: codec, delta logic, and proto schema stay aligned
ARRAY_FIELDS = (
    "assignment",
    "leader_slot",
    "replica_disk",
    "partition_topic",
    "partition_immovable",
    "leader_load",
    "follower_load",
    "broker_capacity",
    "broker_rack",
    "broker_host",
    "broker_alive",
    "broker_new",
    "broker_excl_replicas",
    "broker_excl_leadership",
    "disk_capacity",
    "disk_alive",
    "topic_min_leaders",
)

#: v2 adds ``broker_host`` (host axis, ref model/Host.java). Decoding is
#: backward compatible: a v1 snapshot without the field builds a model with
#: the one-host-per-broker default.
SCHEMA_VERSION = 2


def model_to_arrays(m: TensorClusterModel, strip_padding: bool = True) -> dict[str, Any]:
    """Dense (unpadded) numpy views of a model, build_model-compatible."""
    valid_p = np.asarray(m.partition_valid)
    valid_b = np.asarray(m.broker_valid)
    P = int(valid_p.sum())
    B = int(valid_b.sum())
    if not strip_padding:
        P, B = m.P, m.B

    def arr(name: str) -> np.ndarray:
        return np.asarray(getattr(m, name))

    out: dict[str, Any] = {
        "version": SCHEMA_VERSION,
        "num_racks": m.num_racks,
        "assignment": arr("assignment")[:P],
        "leader_slot": arr("leader_slot")[:P],
        "replica_disk": arr("replica_disk")[:P],
        "partition_topic": arr("partition_topic")[:P],
        "partition_immovable": arr("partition_immovable")[:P],
        "leader_load": arr("leader_load")[:, :P],
        "follower_load": arr("follower_load")[:, :P],
        "broker_capacity": arr("broker_capacity")[:, :B],
        "broker_rack": arr("broker_rack")[:B],
        "broker_host": arr("broker_host")[:B],
        "broker_alive": arr("broker_alive")[:B],
        "broker_new": arr("broker_new")[:B],
        "broker_excl_replicas": arr("broker_excl_replicas")[:B],
        "broker_excl_leadership": arr("broker_excl_leadership")[:B],
        "disk_capacity": arr("disk_capacity")[:B],
        "disk_alive": arr("disk_alive")[:B],
        "topic_min_leaders": arr("topic_min_leaders"),
    }
    return out


def arrays_to_model(d: dict[str, Any], pad: bool = True) -> TensorClusterModel:
    if d.get("version", 1) > SCHEMA_VERSION:
        raise ValueError(f"unsupported snapshot version {d['version']}")
    kwargs = {k: np.asarray(d[k]) for k in ARRAY_FIELDS if k in d}
    return build_model(num_racks=d.get("num_racks"), pad=pad, **kwargs)


# ----- JSON ----------------------------------------------------------------

def to_json(m: TensorClusterModel) -> str:
    d = model_to_arrays(m)
    enc = {
        k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in d.items()
    }
    return json.dumps(enc)


def from_json(s: str) -> TensorClusterModel:
    return arrays_to_model(json.loads(s))


# ----- msgpack (wire) ------------------------------------------------------

def _unpack_array(d: dict) -> np.ndarray:
    a = np.frombuffer(d["b"], dtype=np.dtype(d["d"])).reshape(d["s"])
    if a.dtype == np.uint8 and d.get("bool"):
        return a.astype(bool)
    return a


_BOOL_FIELDS = {
    "partition_immovable", "broker_alive", "broker_new",
    "broker_excl_replicas", "broker_excl_leadership", "disk_alive",
    "topic_min_leaders",
}


def pack_arrays(d: dict[str, Any]) -> bytes:
    """msgpack-encode an arrays dict (full snapshot, delta fields, or a
    columnar result blob).

    Canonical bytes (map keys sorted, recursively — ``ccx.sidecar.wire``
    owns the rule) so fixture generation is deterministic and a JVM
    encoder emitting sorted keys reproduces snapshots byte-exact.

    Hot-path note (round 15): the bytes are built canonically by
    CONSTRUCTION — top-level keys emitted sorted, array entries built in
    their sorted key order (``b`` < ``bool`` < ``d`` < ``s``) — instead
    of routing the finished dict through ``wire.canonicalize``'s
    recursive deep copy. The result-path blobs (columnar diffs at fleet
    rates) pack without an extra full-tree walk, and the emitted bytes
    are IDENTICAL to the old path (``gen_wire_fixtures.py --check`` pins
    byte-stability)."""
    import msgpack

    from ccx.sidecar.wire import canonicalize

    enc: dict[str, Any] = {}
    for k in sorted(d):
        v = d[k]
        if isinstance(v, np.ndarray):
            a = np.ascontiguousarray(v)
            if a.dtype == np.bool_:
                a = a.astype(np.uint8)
            if a.dtype == np.int64:
                a = a.astype(np.int32)
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            p: dict[str, Any] = {"b": a.tobytes()}
            if k in _BOOL_FIELDS:
                p["bool"] = True
            p["d"] = a.dtype.str
            p["s"] = list(a.shape)
            enc[k] = p
        else:
            # scalars pass through; the rare non-array container (never
            # on the hot path) still gets the canonical recursive sort
            enc[k] = canonicalize(v)
    return msgpack.packb(enc, use_bin_type=True)


def to_msgpack(m: TensorClusterModel) -> bytes:
    return pack_arrays(model_to_arrays(m))


def from_msgpack(buf: bytes) -> TensorClusterModel:
    d = decode_msgpack(buf)
    return arrays_to_model(d)


def decode_msgpack(buf: bytes) -> dict[str, Any]:
    import msgpack

    raw = msgpack.unpackb(buf, raw=False)
    out: dict[str, Any] = {}
    for k, v in raw.items():
        out[k] = _unpack_array(v) if isinstance(v, dict) and "b" in v else v
    return out


# ----- deltas (generation-keyed) -------------------------------------------

def delta_encode(base: dict[str, Any], new: dict[str, Any]) -> dict[str, Any]:
    """Fields of ``new`` that differ from ``base`` (plus scalars)."""
    out: dict[str, Any] = {"version": new.get("version", SCHEMA_VERSION),
                           "num_racks": new.get("num_racks")}
    for k in ARRAY_FIELDS:
        if k not in new:
            continue
        a, b = base.get(k), new[k]
        if a is None or np.asarray(a).shape != np.asarray(b).shape or not np.array_equal(a, b):
            out[k] = b
    return out


def delta_apply(base: dict[str, Any], delta: dict[str, Any]) -> dict[str, Any]:
    out = dict(base)
    out.update({k: v for k, v in delta.items() if v is not None})
    return out
