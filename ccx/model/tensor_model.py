"""TensorClusterModel — the cluster as a frozen pytree of device arrays.

This replaces the reference's mutable object tree ``model/ClusterModel.java``
(racks -> hosts -> brokers -> disks -> replicas, SURVEY.md C1/C2): instead of
objects with pointers, the cluster is a set of padded, statically-shaped
arrays so the whole goal stack can be scored on TPU in one fused XLA program
and thousands of candidate assignments can be vmapped.

Layout (P = padded partitions, R = max replication factor, B = padded
brokers, D = max disks/broker, T = topics; RES = NUM_RESOURCES):

* ``assignment  : int32[P, R]``  broker index per replica slot, -1 = no slot.
  Slot order is the *preferred* replica order (slot 0 = preferred leader,
  mirroring Kafka's replica list order used by PreferredLeaderElectionGoal).
* ``leader_slot : int32[P]``     which slot currently leads.
* ``replica_disk: int32[P, R]``  disk index on the hosting broker (JBOD).
* ``leader_load / follower_load : float32[RES, P]`` — the load a replica of
  partition p exerts depending on role. Parity: the reference stores a
  ``Load`` per replica and derives follower CPU/NW from the leader's via
  ``model/ModelUtils.java`` (SURVEY.md C3/C6); we keep both role profiles so
  leadership transfer re-weights loads without re-aggregation. NW_OUT of a
  follower is 0 (only leaders serve consumers); follower NW_IN equals the
  leader's NW_IN (replication traffic); DISK is role-independent.
* broker-axis arrays: capacity, rack id, host id (multi-broker hosts, ref
  ``model/Host.java``), liveness, validity, new-broker and exclusion masks;
  disk-axis capacity/liveness for JBOD.
* ``partition_topic: int32[P]`` and topic-level masks (excluded topics,
  min-leaders topics).

Padding convention: invalid entries are masked (valid=False) and their loads
are zero, so every kernel can reduce over full axes without branching.
Pad sizes should be bucketed (powers of two) by the caller so XLA recompiles
only per bucket, not per cluster size (SURVEY.md section 7.4 "shape dynamism").
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import struct

from ccx.common.resources import NUM_RESOURCES, Resource


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= n (>= minimum)."""
    b = minimum
    while b < n:
        b *= 2
    return b


@struct.dataclass
class TensorClusterModel:
    # --- partition / replica axis ---
    assignment: jnp.ndarray        # int32[P, R]
    leader_slot: jnp.ndarray       # int32[P]
    replica_disk: jnp.ndarray      # int32[P, R]
    partition_valid: jnp.ndarray   # bool[P]
    partition_topic: jnp.ndarray   # int32[P]
    partition_immovable: jnp.ndarray  # bool[P] (excluded-topics option)
    leader_load: jnp.ndarray       # float32[RES, P]
    follower_load: jnp.ndarray     # float32[RES, P]

    # --- broker axis ---
    broker_capacity: jnp.ndarray   # float32[RES, B]
    broker_rack: jnp.ndarray       # int32[B]
    #: host id per broker (ref model/Host.java: rack -> host -> broker).
    #: Multi-broker hosts share an id; default is one host per broker.
    #: Upstream rack-awareness falls back to host distinctness when racks
    #: are unset — build_model implements that by deriving broker_rack from
    #: broker_host when no racks are given, so every rack goal inherits the
    #: fallback without kernel changes.
    broker_host: jnp.ndarray       # int32[B]
    broker_valid: jnp.ndarray      # bool[B]
    broker_alive: jnp.ndarray      # bool[B]  (False => demoted-dead / failed)
    broker_new: jnp.ndarray        # bool[B]  (added brokers, move-target pref)
    broker_excl_replicas: jnp.ndarray    # bool[B] (may not *receive* replicas)
    broker_excl_leadership: jnp.ndarray  # bool[B] (may not hold leadership)

    # --- disk axis (JBOD) ---
    disk_capacity: jnp.ndarray     # float32[B, D]
    disk_alive: jnp.ndarray        # bool[B, D]

    # --- topic axis ---
    topic_min_leaders: jnp.ndarray  # bool[T] (MinTopicLeadersPerBrokerGoal set)

    # --- static metadata (not traced) ---
    num_topics: int = struct.field(pytree_node=False)
    num_racks: int = struct.field(pytree_node=False)

    # ----- shapes -----
    @property
    def P(self) -> int:
        return self.assignment.shape[0]

    @property
    def R(self) -> int:
        return self.assignment.shape[1]

    @property
    def B(self) -> int:
        return self.broker_rack.shape[0]

    @property
    def D(self) -> int:
        return self.disk_capacity.shape[1]

    @property
    def replica_valid(self) -> jnp.ndarray:
        """bool[P, R] — slot holds a replica."""
        return (self.assignment >= 0) & self.partition_valid[:, None]

    @property
    def is_leader(self) -> jnp.ndarray:
        """bool[P, R] — slot is the current leader of its partition."""
        slot_ids = jnp.arange(self.R, dtype=jnp.int32)[None, :]
        return (slot_ids == self.leader_slot[:, None]) & self.replica_valid

    @property
    def replica_load(self) -> jnp.ndarray:
        """float32[RES, P, R] — role-resolved load of each replica slot."""
        lead = self.is_leader[None, :, :]
        load = jnp.where(
            lead, self.leader_load[:, :, None], self.follower_load[:, :, None]
        )
        return jnp.where(self.replica_valid[None, :, :], load, 0.0)

    @property
    def n_alive_brokers(self) -> jnp.ndarray:
        return jnp.sum(self.broker_valid & self.broker_alive)

    @property
    def n_partitions(self) -> jnp.ndarray:
        return jnp.sum(self.partition_valid)

    @property
    def n_replicas(self) -> jnp.ndarray:
        return jnp.sum(self.replica_valid)


def build_model(
    *,
    assignment: np.ndarray,
    leader_load: np.ndarray,
    follower_load: np.ndarray,
    broker_capacity: np.ndarray,
    broker_rack: np.ndarray | None = None,
    broker_host: np.ndarray | None = None,
    partition_topic: np.ndarray | None = None,
    leader_slot: np.ndarray | None = None,
    replica_disk: np.ndarray | None = None,
    broker_alive: np.ndarray | None = None,
    broker_new: np.ndarray | None = None,
    broker_excl_replicas: np.ndarray | None = None,
    broker_excl_leadership: np.ndarray | None = None,
    partition_immovable: np.ndarray | None = None,
    disk_capacity: np.ndarray | None = None,
    disk_alive: np.ndarray | None = None,
    topic_min_leaders: np.ndarray | None = None,
    num_racks: int | None = None,
    pad: bool = True,
) -> TensorClusterModel:
    """Assemble + pad a TensorClusterModel from dense numpy inputs.

    ``assignment`` is int[P, R] with -1 for absent slots; all other arrays are
    unpadded and sized to the true P / B / D / T. With ``pad=True`` the P and
    B axes are grown to power-of-two buckets so repeated builds of similar
    clusters hit the jit cache.
    """
    assignment = np.asarray(assignment, np.int32)
    P, R = assignment.shape
    broker_capacity = np.asarray(broker_capacity, np.float32)
    B = int(broker_capacity.reshape(NUM_RESOURCES, -1).shape[1])
    leader_load = np.asarray(leader_load, np.float32).reshape(NUM_RESOURCES, P)
    follower_load = np.asarray(follower_load, np.float32).reshape(NUM_RESOURCES, P)
    broker_capacity = broker_capacity.reshape(NUM_RESOURCES, B)
    if broker_host is None:
        broker_host = np.arange(B, dtype=np.int32)  # one host per broker
    broker_host = np.asarray(broker_host, np.int32)
    if broker_rack is None:
        # upstream semantics (model/Rack.java via ClusterModel.createBroker):
        # a broker with no rack information is treated as rack == its host,
        # so rack-awareness degrades to host distinctness. Densified: host
        # ids need not be dense, and num_racks is derived as max+1 — sparse
        # ids would inflate it with phantom racks and tighten the
        # RackAwareDistribution per-rack cap ceil(rf / num_racks) wrongly.
        broker_rack = np.unique(broker_host, return_inverse=True)[1]
    broker_rack = np.asarray(broker_rack, np.int32)

    if partition_topic is None:
        partition_topic = np.zeros(P, np.int32)
    partition_topic = np.asarray(partition_topic, np.int32)
    T = int(partition_topic.max(initial=0)) + 1
    if leader_slot is None:
        leader_slot = np.zeros(P, np.int32)
    if replica_disk is None:
        replica_disk = np.where(assignment >= 0, 0, -1).astype(np.int32)
    if disk_capacity is None:
        # Single-disk brokers: the disk is the broker's DISK capacity.
        disk_capacity = broker_capacity[Resource.DISK][:, None].copy()
    disk_capacity = np.asarray(disk_capacity, np.float32)
    D = disk_capacity.shape[1]
    if disk_alive is None:
        disk_alive = np.ones((B, D), bool)
    if broker_alive is None:
        broker_alive = np.ones(B, bool)
    if broker_new is None:
        broker_new = np.zeros(B, bool)
    if broker_excl_replicas is None:
        broker_excl_replicas = np.zeros(B, bool)
    if broker_excl_leadership is None:
        broker_excl_leadership = np.zeros(B, bool)
    if partition_immovable is None:
        partition_immovable = np.zeros(P, bool)
    if topic_min_leaders is None:
        topic_min_leaders = np.zeros(T, bool)
    topic_min_leaders = np.asarray(topic_min_leaders, bool)
    T = max(T, topic_min_leaders.shape[0])
    if pad:
        # Bucket T too — topic-count jitter otherwise changes the [T, B]
        # aggregate shapes and defeats the jit cache.
        T = bucket_size(T, 4)
    topic_min_leaders = np.pad(topic_min_leaders, (0, T - topic_min_leaders.shape[0]))
    if num_racks is None:
        num_racks = int(broker_rack.max(initial=0)) + 1

    if pad:
        Pp, Bp = bucket_size(P, 64), bucket_size(B, 8)
    else:
        Pp, Bp = P, B

    def pad_p(a: np.ndarray, fill: Any = 0) -> np.ndarray:
        width = [(0, Pp - P)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, width, constant_values=fill)

    def pad_b(a: np.ndarray, fill: Any = 0, axis: int = 0) -> np.ndarray:
        width = [(0, 0)] * a.ndim
        width[axis] = (0, Bp - B)
        return np.pad(a, width, constant_values=fill)

    partition_valid = pad_p(np.ones(P, bool))
    broker_valid = pad_b(np.ones(B, bool))

    return TensorClusterModel(
        assignment=jnp.asarray(pad_p(assignment, -1)),
        leader_slot=jnp.asarray(pad_p(np.asarray(leader_slot, np.int32))),
        replica_disk=jnp.asarray(pad_p(np.asarray(replica_disk, np.int32), -1)),
        partition_valid=jnp.asarray(partition_valid),
        partition_topic=jnp.asarray(pad_p(partition_topic)),
        partition_immovable=jnp.asarray(pad_p(np.asarray(partition_immovable, bool))),
        leader_load=jnp.asarray(np.pad(leader_load, [(0, 0), (0, Pp - P)])),
        follower_load=jnp.asarray(np.pad(follower_load, [(0, 0), (0, Pp - P)])),
        broker_capacity=jnp.asarray(pad_b(broker_capacity, axis=1)),
        broker_rack=jnp.asarray(pad_b(broker_rack)),
        # padding hosts get fresh ids so a padded slot can never alias a
        # real multi-broker host (broker_valid masks them everywhere anyway)
        broker_host=jnp.asarray(
            pad_b(broker_host)
            if Bp == B
            else np.concatenate(
                [
                    broker_host,
                    broker_host.max(initial=-1)
                    + 1
                    + np.arange(Bp - B, dtype=np.int32),
                ]
            )
        ),
        broker_valid=jnp.asarray(broker_valid),
        broker_alive=jnp.asarray(pad_b(np.asarray(broker_alive, bool))),
        broker_new=jnp.asarray(pad_b(np.asarray(broker_new, bool))),
        broker_excl_replicas=jnp.asarray(
            pad_b(np.asarray(broker_excl_replicas, bool))
        ),
        broker_excl_leadership=jnp.asarray(
            pad_b(np.asarray(broker_excl_leadership, bool))
        ),
        disk_capacity=jnp.asarray(pad_b(disk_capacity)),
        disk_alive=jnp.asarray(pad_b(np.asarray(disk_alive, bool))),
        topic_min_leaders=jnp.asarray(topic_min_leaders),
        num_topics=T,
        num_racks=num_racks,
    )


def model_dims(m: TensorClusterModel) -> dict[str, int]:
    return {"P": m.P, "R": m.R, "B": m.B, "D": m.D, "T": m.num_topics}
