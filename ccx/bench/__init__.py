"""Benchmark-corpus helpers (scenario generator lives here so the bench,
the tests and the tools import ONE seeded source of adversarial
workloads)."""
