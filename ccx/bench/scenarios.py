"""Adversarial scenario corpus — seeded structural/elasticity workloads.

Every banked quality and latency number through round 17 came from ONE
clean static snapshot per config; the optimizer exists for the messy
cases — broker failures, full disks, hot-topic skew, capacity waves,
partition-count changes. The elasticity papers ("On Efficiently
Partitioning a Topic in Apache Kafka", arxiv 2205.09415; the
consumer-group autoscalers, 2402.06085/2206.11170 — PAPERS.md) argue
these events are the production COMMON case, not the exception.

This module is the generator: each **family** is a seeded, deterministic
sequence of snapshot windows derived from a converged base — exactly the
delta-snapshot stream a JVM LoadMonitor would send while the event
unfolds — with

* **shape stability by construction**: every window of every family
  keeps the base's padded program-shape key (``shape_key``: pow2 P/B/T
  buckets, the pow2 ``max_partitions_per_topic`` bucket, R, D,
  num_racks) — the precondition for the whole family × window matrix
  running ZERO-COMPILE after one prewarm pass. ``generate`` asserts it;
  a family that would cross a bucket is a bug here, not a recompile
  downstream;
* a **pinned quality envelope** per family (``ENVELOPES``): after each
  window's re-optimization the hard tiers must be clean (the result must
  verify — ``require_hard_zero`` stays on) and every soft goal tier must
  land within ``clean * mult + add`` of the clean converged baseline
  banked before any damage. The bounds are pinned here, scale-free
  (relative to the same cluster's own clean solve), and gated by
  ``tools/bench_ledger.py --check`` once banked;
* an **anomaly-verb mapping** (``ANOMALY_VERB``): the facade verb a
  detector would fire for the family's event — the warm-path routing
  story (a detector event is just a metrics window with structural
  damage; the round-14 repair + warm-SA pipeline self-heals it at
  steady-state latency instead of a cold solve).

Families (``FAMILIES``):

* ``broker-failures`` — cascading 1→k dead brokers across distinct
  racks, one more per window (the fix-offline-replicas event);
* ``disk-evacuation`` — one victim broker's disk progressively FILLS
  (its DISK capacity ramps below its clean-base usage), forcing the
  capacity repair to evacuate another slice of stored bytes each
  window;
* ``hot-skew`` — the densest topic's CPU/NW loads spike through a ramp
  (2× → 8×) and partially recover (the goal-violation / metric-anomaly
  event; metrics-only, so windows stay delta-graftable);
* ``broker-wave`` — capacity wave: add brokers (two windows), then
  demote ONE incumbent (leadership exclusion), then remove one
  (evacuation) — the add/demote/remove verb chain;
* ``partition-change`` — a topic's partition count grows each window
  (controller-style rack-striped round-robin placement for the new
  partitions), within the padded P bucket and the topic's pow2
  member bucket.

Stdlib + numpy only on the generation path (the bench imports it before
jax init; the ledger/tools can import it headless).
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: generation order is the documentation order — the bench runs the
#: matrix in this order and SCENARIO_r*.json keys its families by it
FAMILIES = (
    "broker-failures",
    "disk-evacuation",
    "hot-skew",
    "broker-wave",
    "partition-change",
)

#: family -> the facade anomaly verb a detector would fire for the event
#: (None = pure elasticity event served through the Propose path). The
#: warm-recovery acceptance gate (ccx bench --scenario) requires at least
#: one VERB-mapped family to recover warm within ~2x the clean steady
#: window p50 — self-healing at steady-state latency, not the cold wall.
ANOMALY_VERB = {
    "broker-failures": "fix_offline_replicas",
    "disk-evacuation": "rebalance",  # DiskCapacityGoal-violation healing
    "hot-skew": "rebalance",  # goal-violation self-healing
    "broker-wave": "add_brokers/demote_brokers/remove_brokers",
    "partition-change": None,
}

#: per-family quality envelope: goal name -> (mult, add) bound applied
#: against the SAME cluster's clean converged baseline —
#: ``after[goal] <= clean[goal] * mult + add``. ``"*"`` is the default
#: for every soft goal the summary reports; per-goal entries override.
#: Hard tiers are not listed: they are gated by verification itself
#: (require_hard_zero — a window that ships hard violations is already a
#: failed window). The bounds are deliberately generous on the
#: distribution tiers for destructive families (k dead brokers of 20
#: concentrate the surviving load — a perfectly healed cluster is
#: legitimately less balanced than the clean one) and tight on the
#: metrics-only family (a skew spike re-balanced at warm budget should
#:  land near the clean frontier).
ENVELOPES: dict[str, dict[str, tuple[float, float]]] = {
    "broker-failures": {"*": (3.0, 64.0)},
    "disk-evacuation": {"*": (3.0, 64.0)},
    "hot-skew": {"*": (2.0, 32.0)},
    # TopicReplicaDistribution's per-topic spread TARGET moves when the
    # broker set grows/shrinks (ceil(members/B) changes for every
    # topic), so the wave family's TRD bound is wider than its usage
    # bounds — the violations jump reflects the new target, not damage
    # the optimizer failed to heal
    "broker-wave": {"*": (3.0, 64.0),
                    "TopicReplicaDistributionGoal": (5.0, 128.0)},
    "partition-change": {"*": (2.0, 48.0)},
}


@dataclasses.dataclass(frozen=True)
class ScenarioOptions:
    """Corpus knobs (config ``optimizer.scenario.*`` / env
    ``CCX_SCENARIO_*`` — the bench applies the env twins)."""

    #: generator seed (``optimizer.scenario.seed``): the whole corpus is
    #: a pure function of (base arrays, seed, windows)
    seed: int = 7
    #: windows per family (``optimizer.scenario.windows``)
    windows: int = 4
    #: families to emit (``optimizer.scenario.families``)
    families: tuple[str, ...] = FAMILIES

    @classmethod
    def from_config(cls, config) -> "ScenarioOptions":
        """Read the ``optimizer.scenario.*`` keys off a
        CruiseControlConfig (the facade/tests construction path)."""
        fams = tuple(config["optimizer.scenario.families"]) or FAMILIES
        unknown = [f for f in fams if f not in FAMILIES]
        if unknown:
            raise ValueError(
                f"unknown scenario families {unknown}; one of {FAMILIES}"
            )
        return cls(
            seed=config["optimizer.scenario.seed"],
            windows=config["optimizer.scenario.windows"],
            families=fams,
        )


@dataclasses.dataclass(frozen=True)
class ScenarioWindow:
    """One emitted window: the FULL dense arrays dict (the bench
    delta-encodes consecutive windows for the wire) plus bookkeeping."""

    label: str
    arrays: dict
    #: True when a non-metric field changed vs the previous window (the
    #: registry rebuild path; False = delta-graftable metrics window)
    structural: bool


# ----- program-shape key -----------------------------------------------------


def _bucket(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def shape_key(arrays: dict) -> tuple:
    """The compiled-program shape family of a dense snapshot: what
    ``build_model`` pads to plus the pow2 ``max_partitions_per_topic``
    bucket that keys every search program. Two snapshots with equal keys
    share ONE compiled program set (the zero-compile contract)."""
    assignment = np.asarray(arrays["assignment"])
    P, R = assignment.shape
    B = np.asarray(arrays["broker_rack"]).shape[0]
    D = np.asarray(arrays["disk_capacity"]).shape[1]
    topic = np.asarray(arrays["partition_topic"])
    T = int(topic.max(initial=0)) + 1
    tml = arrays.get("topic_min_leaders")
    if tml is not None:
        T = max(T, np.asarray(tml).shape[0])
    maxpt = max(int(np.bincount(topic, minlength=T).max(initial=1)), 1)
    return (
        _bucket(P, 64),
        _bucket(B, 8),
        R,
        D,
        _bucket(T, 4),
        max(1 << (maxpt - 1).bit_length(), 8),
        int(arrays.get("num_racks") or 1),
    )


# ----- generation ------------------------------------------------------------


def generate(family: str, base_arrays: dict,
             opts: ScenarioOptions = ScenarioOptions()) -> list[ScenarioWindow]:
    """The family's seeded window sequence against a converged base.

    ``base_arrays`` is the dense ``model_to_arrays`` dict of the APPLIED
    clean state (the cold proposal's placement written back — what the
    cluster looks like the moment before the event). Windows are
    cumulative: window i's arrays build on window i-1's, exactly like
    the delta stream a live monitor would produce. Raises ``KeyError``
    on an unknown family and ``ValueError`` when the base has no
    headroom for the family inside its padded buckets (the generator
    never silently emits a bucket-crossing window)."""
    gen = _GENERATORS.get(family)
    if gen is None:
        raise KeyError(f"unknown scenario family {family!r}; one of {FAMILIES}")
    rng = np.random.default_rng(
        np.random.SeedSequence([opts.seed, FAMILIES.index(family)])
    )
    windows = gen(_copy_arrays(base_arrays), rng, max(opts.windows, 1))
    key0 = shape_key(base_arrays)
    for w in windows:
        key = shape_key(w.arrays)
        if key != key0:
            raise AssertionError(
                f"{family} window {w.label!r} crossed a program-shape "
                f"bucket: {key0} -> {key} — the zero-compile contract "
                "requires the generator to stay inside the base's buckets"
            )
    return windows


def _copy_arrays(arrays: dict) -> dict:
    return {
        k: (np.array(v) if isinstance(v, np.ndarray) else v)
        for k, v in arrays.items()
    }


def _alive_idx(arrays: dict) -> np.ndarray:
    return np.nonzero(np.asarray(arrays["broker_alive"], bool))[0]


def _racks_of(arrays: dict) -> np.ndarray:
    return np.asarray(arrays["broker_rack"])


def _gen_broker_failures(arrays, rng, n_windows) -> list[ScenarioWindow]:
    """Cascading failures: one MORE broker dies per window, chosen to
    spread across distinct racks first (a rack-correlated cascade is the
    adversarial shape rack-aware goals exist for)."""
    racks = _racks_of(arrays)
    alive = list(_alive_idx(arrays))
    rng.shuffle(alive)
    # distinct racks first, then wrap
    order: list[int] = []
    seen_racks: set[int] = set()
    for b in list(alive):
        if int(racks[b]) not in seen_racks:
            order.append(b)
            seen_racks.add(int(racks[b]))
    order += [b for b in alive if b not in order]
    # never kill more than half the alive set: the scenario is damage,
    # not an unsatisfiable cluster (capacity headroom is ~2.5x) — and
    # the corpus never silently truncates (a shorter family would slip
    # into the same ledger trend group as full rounds)
    kmax = max(len(alive) // 2, 1)
    if n_windows > kmax:
        raise ValueError(
            f"broker-failures: base supports at most {kmax} cascade "
            f"windows (half the alive set); asked for {n_windows}"
        )
    out = []
    for i in range(n_windows):
        dead = order[i]
        ba = np.array(arrays["broker_alive"], bool)
        ba[dead] = False
        arrays["broker_alive"] = ba
        out.append(ScenarioWindow(
            label=f"kill-broker-{int(dead)} (cascade {i + 1})",
            arrays=_copy_arrays(arrays), structural=True,
        ))
    return out


#: the disk-fill ramp: the victim's DISK capacity per window, as a
#: fraction of its usage at the clean base. Against the analyzer's 0.8
#: capacity threshold, window 1 forces ~28 % of the victim's stored
#: bytes off and each later window another ~8-10 % — the progressive
#: fill a retention miss actually looks like. (A NEW fully-full victim
#: per window was measured unsatisfiable for the cold pipeline too at
#: B3: shedding >50 % of a big broker repeatedly while the cluster
#: tightens outruns the repair sweep budget — the scenario must be
#: adversarial, not impossible.)
_DISK_FULL_RAMP = (0.9, 0.78, 0.68, 0.6)


def _broker_disk_usage(arrays: dict) -> np.ndarray:
    """f64[B] — DISK bytes hosted per broker under the snapshot's
    placement (role-resolved: leader slots take leader_load, the rest
    follower_load)."""
    assignment = np.asarray(arrays["assignment"])
    leader_slot = np.asarray(arrays["leader_slot"])
    B = np.asarray(arrays["broker_rack"]).shape[0]
    lead_d = np.asarray(arrays["leader_load"], np.float64)[3]
    fol_d = np.asarray(arrays["follower_load"], np.float64)[3]
    P, R = assignment.shape
    is_lead = np.arange(R)[None, :] == leader_slot[:, None]
    load = np.where(is_lead, lead_d[:, None], fol_d[:, None])
    usage = np.zeros(B)
    valid = assignment >= 0
    np.add.at(usage, assignment[valid], load[valid])
    return usage


def _gen_disk_evacuation(arrays, rng, n_windows) -> list[ScenarioWindow]:
    """Full-disk evacuation: ONE victim broker's disk progressively
    FILLS — its DISK capacity ramps down below what it hosted at the
    clean base (a log-retention miss, a compaction backlog), so each
    window the capacity repair must evacuate another slice of stored
    bytes to get back under the analyzer's capacity line. Exercises the
    capacity-shedding damage class (vs broker-failures' dead-broker
    class) and works on single-disk bases."""
    alive = list(_alive_idx(arrays))
    rng.shuffle(alive)
    victim = int(alive[0])
    usage0 = _broker_disk_usage(arrays)[victim]
    out = []
    for i in range(n_windows):
        # past the pinned ramp the disk keeps filling gently (a repeated
        # final factor would emit byte-identical windows — empty deltas
        # counted as recovery windows)
        if i < len(_DISK_FULL_RAMP):
            frac = _DISK_FULL_RAMP[i]
        else:
            frac = _DISK_FULL_RAMP[-1] * 0.95 ** (
                i - len(_DISK_FULL_RAMP) + 1
            )
        cap = np.array(arrays["broker_capacity"], np.float32)
        new_cap = np.float32(max(usage0 * frac, 1.0))
        scale = new_cap / max(float(cap[3, victim]), 1e-9)
        cap[3, victim] = new_cap
        arrays["broker_capacity"] = cap
        # JBOD invariant: broker DISK capacity == sum of its disks
        dc = np.array(arrays["disk_capacity"], np.float32)
        dc[victim, :] *= np.float32(scale)
        arrays["disk_capacity"] = dc
        out.append(ScenarioWindow(
            label=f"disk-fill-broker-{victim} (cap {frac:g}x base usage)",
            arrays=_copy_arrays(arrays), structural=True,
        ))
    return out


#: the hot-skew ramp: spike factors per window relative to the BASE
#: loads (not cumulative products — the last window is the partial
#: recovery that proves the warm loop re-balances back down too)
_SKEW_RAMP = (2.0, 4.0, 8.0, 2.0)


def _gen_hot_skew(arrays, rng, n_windows) -> list[ScenarioWindow]:
    """Hot-topic skew spike: the densest topic's CPU/NW loads ramp up
    then partially recover. Metrics-only by construction (loads are the
    only fields touched), so every window rides the registry's
    delta-graft fast path and the warm run's drift scan."""
    topic = np.asarray(arrays["partition_topic"])
    counts = np.bincount(topic, minlength=int(topic.max(initial=0)) + 1)
    hot_topic = int(np.argmax(counts))
    mask = topic == hot_topic
    base_lead = np.asarray(arrays["leader_load"], np.float32).copy()
    base_fol = np.asarray(arrays["follower_load"], np.float32).copy()
    # CPU / NW_IN / NW_OUT spike; DISK stays (a consumer storm moves
    # bytes and cycles, not stored data) — rows 0..2 of RES=4
    rows = (0, 1, 2)
    out = []
    for i in range(n_windows):
        # beyond one ramp cycle the spike amplifies per cycle: a bare
        # modulo would make window 5 repeat window 4's factor exactly
        # (ramp ends and restarts at x2) — a byte-identical window whose
        # empty delta would count as a recovery window
        f = _SKEW_RAMP[i % len(_SKEW_RAMP)] * (
            1.0 + 0.25 * (i // len(_SKEW_RAMP))
        )
        lead = base_lead.copy()
        fol = base_fol.copy()
        for r in rows:
            lead[r, mask] *= f
            fol[r, mask] *= f
        arrays["leader_load"] = lead
        arrays["follower_load"] = fol
        out.append(ScenarioWindow(
            label=f"hot-topic-{hot_topic} x{f:g}",
            arrays=_copy_arrays(arrays), structural=False,
        ))
    return out


def _gen_broker_wave(arrays, rng, n_windows) -> list[ScenarioWindow]:
    """Capacity wave: two add windows (new brokers join, empty and
    marked ``broker_new``), one demote window (ONE incumbent loses
    leadership eligibility per window), one remove window (one
    incumbent marked dead for evacuation) — the add/demote/remove verb
    chain as one cumulative event, inside the padded B bucket."""
    B = int(np.asarray(arrays["broker_rack"]).shape[0])
    Bp = _bucket(B, 8)
    head = Bp - B
    n_add = min(max(head // 2, 1), 4) if head else 0
    if head == 0:
        raise ValueError(
            "broker-wave needs B-bucket headroom; base is exactly at its "
            f"pow2 bucket ({B})"
        )
    racks = _racks_of(arrays)
    num_racks = int(arrays.get("num_racks") or int(racks.max()) + 1)
    out = []
    plan = ["add", "add", "demote", "remove"]
    added_total = 0
    alive0 = list(_alive_idx(arrays))
    rng.shuffle(alive0)
    # disjoint victim pools walked by pointer, so every window changes
    # state (a re-demote/re-remove of the same broker would be an empty
    # delta counted as a recovery window); ONE broker per demote — a
    # partition whose WHOLE replica set is demoted has no legal leader
    # without a replica move, so real demotes roll one broker at a time
    # (replica sets never duplicate a broker, making a single demote
    # always healable by a leadership transfer). Removals are bounded
    # to a third of the alive set: the wave is damage, not an
    # unsatisfiable cluster.
    demote_pool = alive0[0::2]
    remove_pool = alive0[1::2][: max(len(alive0) // 3, 1)]
    di = ri = 0
    for i in range(n_windows):
        step = plan[i % len(plan)]
        if step == "add" and added_total + n_add > head:
            step = "demote"  # B bucket full: the wave keeps rolling
        if step == "demote" and di >= len(demote_pool):
            step = "remove"
        if step == "remove" and ri >= len(remove_pool):
            step = "demote" if di < len(demote_pool) else None
        if step == "add":
            arrays = _append_brokers(arrays, n_add, num_racks)
            added_total += n_add
            label = f"add-{n_add}-brokers (wave {i + 1})"
        elif step == "demote":
            excl = np.array(arrays["broker_excl_leadership"], bool)
            victim = demote_pool[di]
            di += 1
            excl[victim] = True
            arrays["broker_excl_leadership"] = excl
            label = f"demote-broker-{int(victim)}"
        elif step == "remove":
            ba = np.array(arrays["broker_alive"], bool)
            victim = remove_pool[ri]
            ri += 1
            ba[victim] = False
            arrays["broker_alive"] = ba
            label = f"remove-broker-{int(victim)}"
        else:
            raise ValueError(
                f"broker-wave: base supports only {i} meaningful "
                f"windows (add headroom, demote and removal pools all "
                f"exhausted); asked for {n_windows}"
            )
        out.append(ScenarioWindow(
            label=label, arrays=_copy_arrays(arrays), structural=True,
        ))
    return out


def _append_brokers(arrays: dict, n: int, num_racks: int) -> dict:
    """Grow every B-axis array by ``n`` fresh brokers: empty, alive,
    ``broker_new``, mean capacity, racks striped round-robin, each on
    its own fresh host."""
    rack0 = np.asarray(arrays["broker_rack"])
    B = rack0.shape[0]
    cap = np.asarray(arrays["broker_capacity"], np.float32)
    new_rack = (np.arange(n) + B) % num_racks
    host0 = np.asarray(arrays["broker_host"])
    new_host = host0.max(initial=-1) + 1 + np.arange(n)
    mean_cap = cap.mean(axis=1, keepdims=True)
    arrays["broker_capacity"] = np.concatenate(
        [cap, np.tile(mean_cap, (1, n)).astype(np.float32)], axis=1
    )
    arrays["broker_rack"] = np.concatenate(
        [rack0, new_rack.astype(rack0.dtype)]
    )
    arrays["broker_host"] = np.concatenate(
        [host0, new_host.astype(host0.dtype)]
    )
    for field, fill in (
        ("broker_alive", True), ("broker_new", True),
        ("broker_excl_replicas", False), ("broker_excl_leadership", False),
    ):
        a = np.asarray(arrays[field], bool)
        arrays[field] = np.concatenate([a, np.full(n, fill, bool)])
    dc = np.asarray(arrays["disk_capacity"], np.float32)
    D = dc.shape[1]
    arrays["disk_capacity"] = np.concatenate(
        [dc, np.tile(mean_cap[3] / D, (n, D)).astype(np.float32)], axis=0
    )
    da = np.asarray(arrays["disk_alive"], bool)
    arrays["disk_alive"] = np.concatenate(
        [da, np.ones((n, D), bool)], axis=0
    )
    return arrays


def _gen_partition_change(arrays, rng, n_windows) -> list[ScenarioWindow]:
    """Partition-count growth (arxiv 2205.09415's elasticity event): a
    mid-sized topic gains partitions each window, placed controller-
    style — rack-striped round-robin over alive brokers, leader slot 0,
    per-partition loads = the topic's per-resource median — all inside
    the padded P bucket AND the pow2 max-partitions-per-topic bucket
    (the program-shape contract)."""
    topic = np.asarray(arrays["partition_topic"])
    P = topic.shape[0]
    Pp = _bucket(P, 64)
    T = int(topic.max(initial=0)) + 1
    counts = np.bincount(topic, minlength=T)
    maxpt = max(int(counts.max(initial=1)), 1)
    maxpt_bucket = max(1 << (maxpt - 1).bit_length(), 8)
    p_head = Pp - P
    if p_head <= 0:
        raise ValueError(
            "partition-change needs P-bucket headroom; base is exactly "
            f"at its pow2 bucket ({P})"
        )
    # any topic may grow to the GLOBAL pow2 max-members bucket without
    # re-keying the programs (the bucket is a capacity); pick the topic
    # with the most bucket headroom (tie: the larger topic — the
    # realistic "split the big topic" event) and size the per-window
    # growth to both the P-bucket and that topic's headroom
    cands = [t for t in range(T) if counts[t] > 0]
    if not cands:
        raise ValueError("partition-change: base has no populated topics")
    grow_topic = int(max(
        cands, key=lambda t: (maxpt_bucket - counts[t], counts[t])
    ))
    headroom = int(maxpt_bucket - counts[grow_topic])
    # NO floor here: flooring at 1 would let total growth overrun a
    # small P-bucket headroom and trip the internal bucket assertion —
    # insufficient headroom must be THIS documented error instead
    per_window = min(
        p_head // max(n_windows, 1),
        headroom // max(n_windows, 1),
    )
    if per_window < 1:
        raise ValueError(
            "partition-change: cannot grow at least one partition per "
            f"window inside the buckets (P headroom {p_head}, topic "
            f"member-bucket headroom {headroom}, {n_windows} windows)"
        )
    out = []
    for i in range(n_windows):
        arrays = _append_partitions(arrays, grow_topic, per_window, rng)
        out.append(ScenarioWindow(
            label=f"grow-topic-{grow_topic}+{per_window} (window {i + 1})",
            arrays=_copy_arrays(arrays), structural=True,
        ))
    return out


def _append_partitions(arrays: dict, topic_id: int, n: int, rng) -> dict:
    """Controller-style creation of ``n`` partitions for ``topic_id``."""
    assignment = np.asarray(arrays["assignment"])
    P, R = assignment.shape
    topic = np.asarray(arrays["partition_topic"])
    mask = topic == topic_id
    # replication factor: the topic's modal live-slot count
    rf = int(np.round((assignment[mask] >= 0).sum(axis=1).mean())) or 1
    rf = max(min(rf, R), 1)
    alive = np.nonzero(
        np.asarray(arrays["broker_alive"], bool)
        & ~np.asarray(arrays["broker_excl_replicas"], bool)
    )[0]
    # controller-style rack-aware spread: slot k of partition p takes
    # rack (rot + p + k) mod NR — replica sets are rack-distinct while
    # rf <= NR — and round-robins brokers within the rack; a broker is
    # never doubled within one partition
    by_rack: dict[int, list[int]] = {}
    rack_of = np.asarray(arrays["broker_rack"])
    for b in alive:
        by_rack.setdefault(int(rack_of[b]), []).append(int(b))
    rack_ids = sorted(by_rack)
    NR = len(rack_ids)
    rot = int(rng.integers(0, NR))
    new_assign = np.full((n, R), -1, np.int32)
    for p in range(n):
        chosen: list[int] = []
        k = 0
        while len(chosen) < rf and k < rf * NR * 4:
            r = rack_ids[(rot + p + k) % NR]
            lst = by_rack[r]
            b = lst[((p + k) // NR) % len(lst)]
            if b not in chosen:
                chosen.append(b)
            k += 1
        new_assign[p, : len(chosen)] = chosen
    arrays["assignment"] = np.concatenate([assignment, new_assign])
    arrays["leader_slot"] = np.concatenate(
        [np.asarray(arrays["leader_slot"]), np.zeros(n, np.int32)]
    )
    rd = np.asarray(arrays["replica_disk"])
    new_rd = np.where(new_assign >= 0, 0, -1).astype(rd.dtype)
    arrays["replica_disk"] = np.concatenate([rd, new_rd])
    arrays["partition_topic"] = np.concatenate(
        [topic, np.full(n, topic_id, topic.dtype)]
    )
    arrays["partition_immovable"] = np.concatenate(
        [np.asarray(arrays["partition_immovable"], bool),
         np.zeros(n, bool)]
    )
    for field in ("leader_load", "follower_load"):
        load = np.asarray(arrays[field], np.float32)
        med = np.median(load[:, mask], axis=1, keepdims=True) if mask.any() \
            else load.mean(axis=1, keepdims=True)
        arrays[field] = np.concatenate(
            [load, np.tile(med, (1, n)).astype(np.float32)], axis=1
        )
    return arrays


_GENERATORS = {
    "broker-failures": _gen_broker_failures,
    "disk-evacuation": _gen_disk_evacuation,
    "hot-skew": _gen_hot_skew,
    "broker-wave": _gen_broker_wave,
    "partition-change": _gen_partition_change,
}


# ----- envelope --------------------------------------------------------------


def goals_after(goal_summary: list[dict]) -> dict[str, float]:
    """goal name -> violationsAfter, soft goals only, from a result's
    ``goalSummary`` block (hard tiers are verification's jurisdiction)."""
    return {
        g["goal"]: float(g["violationsAfter"])
        for g in goal_summary or ()
        if not g.get("hard")
    }


def check_envelope(family: str, clean: dict[str, float],
                   after: dict[str, float]) -> list[str]:
    """Envelope failures for one recovered window: every soft goal's
    violations must land within ``clean * mult + add`` of the clean
    converged baseline (``ENVELOPES[family]``; ``"*"`` is the family
    default, per-goal entries override). Returns [] when inside."""
    env = ENVELOPES.get(family)
    if env is None:
        raise KeyError(f"no envelope pinned for family {family!r}")
    default = env.get("*")
    failures = []
    for goal, got in sorted(after.items()):
        mult, add = env.get(goal, default) or (None, None)
        if mult is None:
            continue
        bound = clean.get(goal, 0.0) * mult + add
        if got > bound:
            failures.append(
                f"{goal}: {got:g} > envelope {bound:g} "
                f"(clean {clean.get(goal, 0.0):g} x{mult:g} + {add:g})"
            )
    return failures
