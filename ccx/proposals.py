"""Execution proposals — diffing pre/post placements.

Parity: ``analyzer/AnalyzerUtils.getDiff`` turns the optimizer's mutated
ClusterModel into a set of ``executor/ExecutionProposal`` records (old/new
replica lists + leaders) that the Executor converts into AdminClient
reassignment calls (SURVEY.md C20/C24, call stack 3.2->3.3). Here the diff
is a vectorized numpy comparison of the placement arrays of two
TensorClusterModels.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from ccx.model.tensor_model import TensorClusterModel


class ActionType(enum.Enum):
    """Parity: ``analyzer/ActionType.java`` (SURVEY.md C20)."""

    INTER_BROKER_REPLICA_MOVEMENT = "inter_broker_replica_movement"
    LEADERSHIP_MOVEMENT = "leadership_movement"
    INTRA_BROKER_REPLICA_MOVEMENT = "intra_broker_replica_movement"


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment (ref: executor/ExecutionProposal.java)."""

    partition: int
    topic: int
    old_replicas: tuple[int, ...]
    new_replicas: tuple[int, ...]
    old_leader: int
    new_leader: int
    old_disks: tuple[int, ...] = ()
    new_disks: tuple[int, ...] = ()

    @property
    def actions(self) -> tuple[ActionType, ...]:
        acts = []
        if set(self.old_replicas) != set(self.new_replicas):
            acts.append(ActionType.INTER_BROKER_REPLICA_MOVEMENT)
        if self.old_leader != self.new_leader:
            acts.append(ActionType.LEADERSHIP_MOVEMENT)
        # A broker present before and after whose replica changed disks is an
        # intra-broker move — independent of any inter-broker change on the
        # partition's *other* replicas.
        old_disk_of = dict(zip(self.old_replicas, self.old_disks))
        if self.old_disks and any(
            b in old_disk_of and old_disk_of[b] != d
            for b, d in zip(self.new_replicas, self.new_disks)
        ):
            acts.append(ActionType.INTRA_BROKER_REPLICA_MOVEMENT)
        return tuple(acts)

    @property
    def data_to_move(self) -> int:
        """Count of replicas that change broker (executor concurrency caps
        are per-movement; per-byte accounting is layered on by the planner)."""
        return len(set(self.new_replicas) - set(self.old_replicas))

    def to_json(self) -> dict:
        out = {
            "topicPartition": {"topic": int(self.topic), "partition": int(self.partition)},
            "oldLeader": int(self.old_leader),
            "newLeader": int(self.new_leader),
            "oldReplicas": [int(b) for b in self.old_replicas],
            "newReplicas": [int(b) for b in self.new_replicas],
        }
        if self.old_disks or self.new_disks:
            out["oldDisks"] = [int(d) for d in self.old_disks]
            out["newDisks"] = [int(d) for d in self.new_disks]
        return out


def diff(before: TensorClusterModel, after: TensorClusterModel) -> list[ExecutionProposal]:
    """All partitions whose placement changed, as ExecutionProposals."""
    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    d0 = np.asarray(before.replica_disk)
    d1 = np.asarray(after.replica_disk)
    pvalid = np.asarray(before.partition_valid)
    topics = np.asarray(before.partition_topic)

    changed = pvalid & (
        np.any(a0 != a1, axis=1) | (l0 != l1) | np.any(d0 != d1, axis=1)
    )
    ps = np.nonzero(changed)[0]
    # Bulk-convert to Python scalars once — per-element numpy indexing is
    # ~100x slower and B5-scale diffs cover ~10^5 partitions.
    rows = zip(
        ps.tolist(),
        topics[ps].tolist(),
        a0[ps].tolist(),
        a1[ps].tolist(),
        l0[ps].tolist(),
        l1[ps].tolist(),
        d0[ps].tolist(),
        d1[ps].tolist(),
    )
    out: list[ExecutionProposal] = []
    for p, t, r0, r1, s0, s1, k0, k1 in rows:
        old_r = tuple(b for b in r0 if b >= 0)
        new_r = tuple(b for b in r1 if b >= 0)
        out.append(
            ExecutionProposal(
                partition=p,
                topic=t,
                old_replicas=old_r,
                new_replicas=new_r,
                old_leader=r0[s0] if old_r else -1,
                new_leader=r1[s1] if new_r else -1,
                old_disks=tuple(d for d, b in zip(k0, r0) if b >= 0),
                new_disks=tuple(d for d, b in zip(k1, r1) if b >= 0),
            )
        )
    return out


def diff_columnar(
    before: TensorClusterModel, after: TensorClusterModel
) -> dict[str, np.ndarray]:
    """`diff` as a dict of dense arrays (one row per changed partition):
    ``partition/topic/oldLeader/newLeader int32[N]``,
    ``oldReplicas/newReplicas/oldDisks/newDisks int32[N, R]`` (-1 pad).

    The proposals-DOWN leg of the sidecar hop dominates its wire cost at
    B5 (~0.9 s of per-proposal msgpack maps for ~60k proposals,
    docs/perf-notes.md "Sidecar-inclusive T1"); columnar rows pack as raw
    little-endian buffers instead. Semantically identical to ``diff`` —
    tests assert row/column agreement.
    """
    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    d0 = np.asarray(before.replica_disk)
    d1 = np.asarray(after.replica_disk)
    pvalid = np.asarray(before.partition_valid)
    topics = np.asarray(before.partition_topic)

    changed = pvalid & (
        np.any(a0 != a1, axis=1) | (l0 != l1) | np.any(d0 != d1, axis=1)
    )
    ps = np.nonzero(changed)[0]
    n = ps.size
    old_lead = np.where(
        (a0[ps] >= 0).any(axis=1), a0[ps, np.clip(l0[ps], 0, a0.shape[1] - 1)], -1
    )
    new_lead = np.where(
        (a1[ps] >= 0).any(axis=1), a1[ps, np.clip(l1[ps], 0, a1.shape[1] - 1)], -1
    )
    return {
        "partition": ps.astype(np.int32),
        "topic": topics[ps].astype(np.int32),
        "oldReplicas": a0[ps].astype(np.int32),
        "newReplicas": a1[ps].astype(np.int32),
        "oldLeader": old_lead.astype(np.int32),
        "newLeader": new_lead.astype(np.int32),
        "oldDisks": np.where(a0[ps] >= 0, d0[ps], -1).astype(np.int32),
        "newDisks": np.where(a1[ps] >= 0, d1[ps], -1).astype(np.int32),
    }
