"""Execution proposals — diffing pre/post placements, columnar-first.

Parity: ``analyzer/AnalyzerUtils.getDiff`` turns the optimizer's mutated
ClusterModel into a set of ``executor/ExecutionProposal`` records (old/new
replica lists + leaders) that the Executor converts into AdminClient
reassignment calls (SURVEY.md C20/C24, call stack 3.2->3.3).

Since round 15 the CANONICAL diff representation is columnar
(``ColumnarDiff``): flat int32 arrays, one row per changed partition, in
the exact ``diff_columnar`` wire schema. The row ``ExecutionProposal``
list is a lazy view derived from the columns only when a consumer
actually asks for rows (executor hand-off, row-mode wire results) — a
warm steady-state window, the columnar sidecar path and the movement
counters never materialize ~62k Python dataclasses at B5.

The diff itself runs as a compiled ON-DEVICE program by default
(``columnar_diff``): a changed-partition mask + count (one scalar sync),
then a prefix-sum compaction that gathers only the changed rows into a
shape-bucketed capacity (one "small" bucket for warm drift windows, one
full-P bucket for cold results — warm and cold each reuse ONE compiled
program per model shape) so only ~N rows cross device→host instead of
eight full [P]-sized arrays. ``CCX_DEVICE_DIFF=0`` (or
``backend="numpy"``) restores the host numpy diff, which stays the
parity reference.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import os

import numpy as np

from ccx.model.tensor_model import TensorClusterModel


class ActionType(enum.Enum):
    """Parity: ``analyzer/ActionType.java`` (SURVEY.md C20)."""

    INTER_BROKER_REPLICA_MOVEMENT = "inter_broker_replica_movement"
    LEADERSHIP_MOVEMENT = "leadership_movement"
    INTRA_BROKER_REPLICA_MOVEMENT = "intra_broker_replica_movement"


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment (ref: executor/ExecutionProposal.java)."""

    partition: int
    topic: int
    old_replicas: tuple[int, ...]
    new_replicas: tuple[int, ...]
    old_leader: int
    new_leader: int
    old_disks: tuple[int, ...] = ()
    new_disks: tuple[int, ...] = ()

    @property
    def actions(self) -> tuple[ActionType, ...]:
        acts = []
        if set(self.old_replicas) != set(self.new_replicas):
            acts.append(ActionType.INTER_BROKER_REPLICA_MOVEMENT)
        if self.old_leader != self.new_leader:
            acts.append(ActionType.LEADERSHIP_MOVEMENT)
        # A broker present before and after whose replica changed disks is an
        # intra-broker move — independent of any inter-broker change on the
        # partition's *other* replicas.
        old_disk_of = dict(zip(self.old_replicas, self.old_disks))
        if self.old_disks and any(
            b in old_disk_of and old_disk_of[b] != d
            for b, d in zip(self.new_replicas, self.new_disks)
        ):
            acts.append(ActionType.INTRA_BROKER_REPLICA_MOVEMENT)
        return tuple(acts)

    @property
    def data_to_move(self) -> int:
        """Count of replicas that change broker (executor concurrency caps
        are per-movement; per-byte accounting is layered on by the planner)."""
        return len(set(self.new_replicas) - set(self.old_replicas))

    def to_json(self) -> dict:
        out = {
            "topicPartition": {"topic": int(self.topic), "partition": int(self.partition)},
            "oldLeader": int(self.old_leader),
            "newLeader": int(self.new_leader),
            "oldReplicas": [int(b) for b in self.old_replicas],
            "newReplicas": [int(b) for b in self.new_replicas],
        }
        if self.old_disks or self.new_disks:
            out["oldDisks"] = [int(d) for d in self.old_disks]
            out["newDisks"] = [int(d) for d in self.new_disks]
        return out


def diff(before: TensorClusterModel, after: TensorClusterModel) -> list[ExecutionProposal]:
    """All partitions whose placement changed, as ExecutionProposals."""
    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    d0 = np.asarray(before.replica_disk)
    d1 = np.asarray(after.replica_disk)
    pvalid = np.asarray(before.partition_valid)
    topics = np.asarray(before.partition_topic)

    changed = pvalid & (
        np.any(a0 != a1, axis=1) | (l0 != l1) | np.any(d0 != d1, axis=1)
    )
    ps = np.nonzero(changed)[0]
    # Bulk-convert to Python scalars once — per-element numpy indexing is
    # ~100x slower and B5-scale diffs cover ~10^5 partitions.
    rows = zip(
        ps.tolist(),
        topics[ps].tolist(),
        a0[ps].tolist(),
        a1[ps].tolist(),
        l0[ps].tolist(),
        l1[ps].tolist(),
        d0[ps].tolist(),
        d1[ps].tolist(),
    )
    out: list[ExecutionProposal] = []
    for p, t, r0, r1, s0, s1, k0, k1 in rows:
        old_r = tuple(b for b in r0 if b >= 0)
        new_r = tuple(b for b in r1 if b >= 0)
        out.append(
            ExecutionProposal(
                partition=p,
                topic=t,
                old_replicas=old_r,
                new_replicas=new_r,
                old_leader=r0[s0] if old_r else -1,
                new_leader=r1[s1] if new_r else -1,
                old_disks=tuple(d for d, b in zip(k0, r0) if b >= 0),
                new_disks=tuple(d for d, b in zip(k1, r1) if b >= 0),
            )
        )
    return out


def diff_columnar(
    before: TensorClusterModel, after: TensorClusterModel
) -> dict[str, np.ndarray]:
    """`diff` as a dict of dense arrays (one row per changed partition):
    ``partition/topic/oldLeader/newLeader int32[N]``,
    ``oldReplicas/newReplicas/oldDisks/newDisks int32[N, R]`` (-1 pad).

    The proposals-DOWN leg of the sidecar hop dominates its wire cost at
    B5 (~0.9 s of per-proposal msgpack maps for ~60k proposals,
    docs/perf-notes.md "Sidecar-inclusive T1"); columnar rows pack as raw
    little-endian buffers instead. Semantically identical to ``diff`` —
    tests assert row/column agreement. This is the HOST numpy form; the
    default production path is the compiled device program behind
    ``columnar_diff`` (bit-identical, test-pinned).
    """
    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    d0 = np.asarray(before.replica_disk)
    d1 = np.asarray(after.replica_disk)
    pvalid = np.asarray(before.partition_valid)
    topics = np.asarray(before.partition_topic)

    changed = pvalid & (
        np.any(a0 != a1, axis=1) | (l0 != l1) | np.any(d0 != d1, axis=1)
    )
    ps = np.nonzero(changed)[0]
    old_lead = np.where(
        (a0[ps] >= 0).any(axis=1), a0[ps, np.clip(l0[ps], 0, a0.shape[1] - 1)], -1
    )
    new_lead = np.where(
        (a1[ps] >= 0).any(axis=1), a1[ps, np.clip(l1[ps], 0, a1.shape[1] - 1)], -1
    )
    return {
        "partition": ps.astype(np.int32),
        "topic": topics[ps].astype(np.int32),
        "oldReplicas": a0[ps].astype(np.int32),
        "newReplicas": a1[ps].astype(np.int32),
        "oldLeader": old_lead.astype(np.int32),
        "newLeader": new_lead.astype(np.int32),
        "oldDisks": np.where(a0[ps] >= 0, d0[ps], -1).astype(np.int32),
        "newDisks": np.where(a1[ps] >= 0, d1[ps], -1).astype(np.int32),
    }


# ----- columnar-canonical diff (round 15) -----------------------------------

#: env override: ``CCX_DEVICE_DIFF=0`` routes every ``columnar_diff``
#: through the host numpy reference; ``=1`` forces the compiled device
#: program regardless of model size; unset applies the size gate below
ENV_DEVICE_DIFF = "CCX_DEVICE_DIFF"

#: padded-P floor for the device diff by default: below it the host
#: numpy diff finishes in well under a millisecond, so compiling two
#: programs per model shape is pure loss (a test suite touches dozens
#: of tiny fixture shapes; serving fleets bucket to a handful of big
#: ones). At and above it — the B5/B6 serving regime — the device path
#: transfers only the changed rows instead of eight full [P] arrays.
DEVICE_DIFF_MIN_P = 8192

#: floor of the "small" compaction bucket (rows). Two capacity buckets per
#: model shape — small for warm drift windows, full-P for cold results —
#: so repeat warm windows and repeat cold solves each reuse ONE compiled
#: compaction program: a fluctuating drift size must never recompile
#: mid-steady-loop (the zero-warm-fresh-compile tripwires ride on this).
SMALL_DIFF_FLOOR = 1024


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _small_cap(P: int) -> int:
    """Small-bucket row capacity for a P-partition model: pow2 of
    max(floor, P/16), clamped to P — covers ~6% of partitions changing,
    an order of magnitude above the steady-state drift contract."""
    return min(_pow2_ceil(max(SMALL_DIFF_FLOOR, P // 16)), P)


def _device_programs():
    """Lazy jit-program pair (mask+count, bucketed compaction) — jax is
    imported on first device diff so row-only consumers stay light."""
    global _DIFF_MASK, _DIFF_COMPACT
    if _DIFF_MASK is not None:
        return _DIFF_MASK, _DIFF_COMPACT
    import jax
    import jax.numpy as jnp

    from ccx.common import costmodel

    @costmodel.instrument("device-diff-mask")
    @jax.jit
    def _mask(pvalid, a0, a1, l0, l1, d0, d1):
        changed = pvalid & (
            jnp.any(a0 != a1, axis=1)
            | (l0 != l1)
            | jnp.any(d0 != d1, axis=1)
        )
        return changed, jnp.sum(changed, dtype=jnp.int32)

    @costmodel.instrument("device-diff-compact")
    @functools.partial(jax.jit, static_argnames=("cap",))
    def _compact(changed, topics, a0, a1, l0, l1, d0, d1, *, cap):
        # prefix-sum compaction: indices of the first `cap` changed rows
        # (ascending partition order, matching np.nonzero); rows past the
        # true count gather partition 0's data and are sliced off on host
        idx = jnp.nonzero(changed, size=cap, fill_value=0)[0]
        g0 = a0[idx]
        g1 = a1[idx]
        R = a0.shape[1]
        s0 = jnp.clip(l0[idx], 0, R - 1)[:, None]
        s1 = jnp.clip(l1[idx], 0, R - 1)[:, None]
        old_lead = jnp.where(
            (g0 >= 0).any(axis=1),
            jnp.take_along_axis(g0, s0, axis=1)[:, 0], -1,
        )
        new_lead = jnp.where(
            (g1 >= 0).any(axis=1),
            jnp.take_along_axis(g1, s1, axis=1)[:, 0], -1,
        )
        return {
            "partition": idx.astype(jnp.int32),
            "topic": topics[idx],
            "oldReplicas": g0,
            "newReplicas": g1,
            "oldLeader": old_lead.astype(jnp.int32),
            "newLeader": new_lead.astype(jnp.int32),
            "oldDisks": jnp.where(g0 >= 0, d0[idx], -1),
            "newDisks": jnp.where(g1 >= 0, d1[idx], -1),
        }

    _DIFF_MASK, _DIFF_COMPACT = _mask, _compact
    return _mask, _compact


_DIFF_MASK = None
_DIFF_COMPACT = None


#: columnar schema field order (the wire blob and every consumer iterate
#: in this order; scalars first, then the [N, R] slot arrays)
COLUMNS = (
    "partition", "topic", "oldReplicas", "newReplicas",
    "oldLeader", "newLeader", "oldDisks", "newDisks",
)


class ColumnarDiff:
    """The canonical diff: one ``diff_columnar``-schema column set, with
    the row ``ExecutionProposal`` list derived lazily (and cached) only
    when a consumer actually wants rows. Movement counters are vectorized
    over the columns, so ``include_proposals=False`` results never touch
    a Python row object."""

    __slots__ = ("cols", "_rows")

    def __init__(self, cols: dict[str, np.ndarray]) -> None:
        self.cols = cols
        self._rows = None

    def __repr__(self) -> str:  # dataclass-embedded: keep it one line
        return f"ColumnarDiff(n={self.n})"

    @property
    def n(self) -> int:
        return int(self.cols["partition"].shape[0])

    def __len__(self) -> int:
        return self.n

    @property
    def num_replica_movements(self) -> int:
        """Sum of per-row ``data_to_move`` (replicas changing broker),
        vectorized: a new replica counts when its broker is absent from
        the row's old set (brokers are distinct within a set, so count
        equals set difference size)."""
        new = self.cols["newReplicas"]
        old = self.cols["oldReplicas"]
        if new.size == 0:
            return 0
        member = (new[:, :, None] == old[:, None, :]).any(axis=2)
        return int(((new >= 0) & ~member).sum())

    @property
    def num_leadership_movements(self) -> int:
        return int((self.cols["oldLeader"] != self.cols["newLeader"]).sum())

    @property
    def rows(self) -> list[ExecutionProposal]:
        """The row view, materialized on first access (bulk ``tolist``
        conversion — per-element numpy indexing is ~100x slower at
        B5-scale diffs)."""
        if self._rows is None:
            c = self.cols
            out: list[ExecutionProposal] = []
            for p, t, r0, r1, s0, s1, k0, k1 in zip(
                c["partition"].tolist(),
                c["topic"].tolist(),
                c["oldReplicas"].tolist(),
                c["newReplicas"].tolist(),
                c["oldLeader"].tolist(),
                c["newLeader"].tolist(),
                c["oldDisks"].tolist(),
                c["newDisks"].tolist(),
            ):
                out.append(
                    ExecutionProposal(
                        partition=p,
                        topic=t,
                        old_replicas=tuple(b for b in r0 if b >= 0),
                        new_replicas=tuple(b for b in r1 if b >= 0),
                        old_leader=s0,
                        new_leader=s1,
                        old_disks=tuple(
                            d for d, b in zip(k0, r0) if b >= 0
                        ),
                        new_disks=tuple(
                            d for d, b in zip(k1, r1) if b >= 0
                        ),
                    )
                )
            self._rows = out
        return self._rows

    def rows_json(self) -> list[dict]:
        return [p.to_json() for p in self.rows]


def columnar_diff(
    before: TensorClusterModel,
    after: TensorClusterModel,
    backend: str | None = None,
) -> ColumnarDiff:
    """The one diff source of the result path (round 15): compiled
    on-device mask + bucketed compaction for serving-scale models
    (``DEVICE_DIFF_MIN_P``), transferring only the changed rows; small
    models (and ``backend="numpy"`` / env ``CCX_DEVICE_DIFF=0``) run the
    host reference, which is cheaper than any compile at that scale.
    Any device-path surprise degrades to the numpy reference — a diff
    must never fail a proposal."""
    if backend is None:
        env = os.environ.get(ENV_DEVICE_DIFF)
        if env == "0":
            backend = "numpy"
        elif env == "1":
            backend = "device"
        else:
            backend = (
                "device" if int(before.P) >= DEVICE_DIFF_MIN_P else "numpy"
            )
    if backend == "device":
        try:
            # chaos seam (ccx.common.faults): an injected device-diff
            # failure exercises exactly this degrade path — the numpy
            # reference below stays the correctness pin
            from ccx.common.faults import FAULTS

            if FAULTS.armed:
                FAULTS.hit("device.diff")
            return ColumnarDiff(_device_diff(before, after))
        except Exception:  # noqa: BLE001 — degrade to the host reference
            import logging

            logging.getLogger(__name__).exception(
                "device diff failed; falling back to numpy"
            )
    return ColumnarDiff(diff_columnar(before, after))


def _device_diff(
    before: TensorClusterModel, after: TensorClusterModel
) -> dict[str, np.ndarray]:
    mask, compact = _device_programs()
    changed, n_dev = mask(
        before.partition_valid,
        before.assignment, after.assignment,
        before.leader_slot, after.leader_slot,
        before.replica_disk, after.replica_disk,
    )
    n = int(n_dev)  # the path's single scalar sync (picks the bucket)
    P = int(before.P)
    small = _small_cap(P)
    cap = small if n <= small else P
    dev = compact(
        changed, before.partition_topic,
        before.assignment, after.assignment,
        before.leader_slot, after.leader_slot,
        before.replica_disk, after.replica_disk,
        cap=cap,
    )
    # one bulk device->host transfer per column, cap rows each; the rows
    # past n gathered partition 0 as filler and are sliced off here
    return {k: np.asarray(dev[k])[:n] for k in COLUMNS}
